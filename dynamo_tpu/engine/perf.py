"""Engine perf plane: compile observatory, roofline-attributed window
timing, and HBM telemetry (docs/OBSERVABILITY.md "Engine perf plane").

The device/compiler layer was the last dark subsystem: tracing covers
requests, the flight recorder covers engine-loop state, the KV pane
covers the cache — but nothing measured *compiles*, per-window device
time, or HBM occupancy, so docs/PERF_NOTES.md's "~34% of roofline" and
"first-long-prompt compile stall" findings were hand-run archaeology.
This module makes them live series:

- ``CompileRegistry``: every ``jax.jit`` program in the serving path is
  built through :func:`instrumented_jit` (enforced by the
  ``unregistered-jit`` lint rule), which wraps the jitted callable and
  detects REAL XLA compiles via ``jax.monitoring``'s backend-compile
  events — dispatch-cache churn (e.g. committed-ness changes) does not
  count (falling back to first-call counting when the monitoring API is
  unavailable). Per program family it records compile counts, actual
  backend-compile seconds, the set of shape-signature keys seen, and a
  one-time FLOPs/bytes cost estimate from ``lower().cost_analysis()``
  (with a typed error fallback on backends without the API).
- **Unexpected-recompile detector** — the runtime twin of the
  ``jit-recompile-hazard`` lint rule: the SAME wrapper (one program
  instance, one shape signature) compiling again after ``mark_ready()``
  (the engine's warmup boundary) means the jit cache was invalidated on
  the serving path (dtype/weak-type drift, shape leak, donation
  mismatch). It bumps ``perf_unexpected_recompiles_total{program}``,
  logs a WARNING, and emits a ``perf.recompile`` span with
  ``status="warn"``. Judged per-wrapper so two runners in one process
  don't cross-flag each other's first compiles; pre-ready compiles are
  never flagged (warmup intentionally double-compiles signatures whose
  input shardings converge after the first run).
- ``note_window``: the engine feeds one (device-seconds, tokens,
  active-slots, steps) sample per processed decode window — plain
  float stores on the engine thread, no locks, no allocation — from
  which the registry derives EWMA step seconds, achieved tok/s, and
  the fraction of the weight-read roofline those tokens achieved.
- ``PerfMetricsUpdater``: throttled exporter (same discipline as
  engine/kv_metrics.py KvMetricsUpdater) turning the registry's plain
  ints into ``dynamo_tpu_perf_*`` counters/gauges, plus periodic
  ``device.memory_stats()`` HBM gauges from the runner.

Env knobs: ``DTPU_PERF_COST`` = ``lower`` (default: cheap unoptimized-
HLO estimate) | ``compile`` (accurate, pays a second XLA compile per
program family) | ``off``.
"""

from __future__ import annotations

import os
import threading
import time

import jax

from dynamo_tpu.runtime.logging import (generate_span_id, generate_trace_id,
                                        get_logger)

log = get_logger("perf")

#: EWMA smoothing for the per-window series (0.2 = ~5-window memory).
_EWMA = 0.2


def _cost_mode() -> str:
    return os.environ.get("DTPU_PERF_COST", "lower").strip().lower()


class _Program:
    """Plain-int per-program-family telemetry (engine-thread writers;
    snapshot readers tolerate torn reads — these are gauges/counters,
    not invariants)."""

    __slots__ = ("name", "compiles", "compile_seconds", "unexpected",
                 "sigs", "cost", "last_compile_ts")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.compile_seconds = 0.0
        self.unexpected = 0
        self.sigs: dict = {}      # signature key -> compile count
        self.cost: dict | None = None  # one-time FLOPs/bytes estimate
        self.last_compile_ts = 0.0


# -- compile detection probe ---------------------------------------------------
# jax.monitoring fires ``/jax/core/compile/backend_compile_duration``
# synchronously in the calling thread for every REAL XLA compile — the
# only signal that separates compiles from dispatch-cache churn (the
# private ``_cache_size`` probe also grows on fast-path entries for
# committed-ness changes, which produced false recompile alarms). The
# listener feeds a thread-local accumulator the wrappers snapshot
# around each call.

_tls = threading.local()


def _probe() -> tuple[int, float]:
    return (getattr(_tls, "n", 0), getattr(_tls, "s", 0.0))


def _on_compile_event(event: str, duration: float, **_kw) -> None:
    if "backend_compile" in event:
        _tls.n = getattr(_tls, "n", 0) + 1
        _tls.s = getattr(_tls, "s", 0.0) + duration


_PROBE_OK = False
try:  # pragma: no branch — registration is once at import
    jax.monitoring.register_event_duration_secs_listener(_on_compile_event)
    _PROBE_OK = True
except Exception:  # noqa: BLE001 — older jax: degrade to first-call counting
    log.info("jax.monitoring unavailable; compile observatory degrades "
             "to first-call counting")


class _InstrumentedJit:
    """Transparent wrapper around one jitted callable: forwards calls,
    counts compiles, triggers one-time cost analysis. One wrapper per
    (program, signature key) — the runner's shape-bucket caches store
    these in place of the raw jitted function."""

    __slots__ = ("_fn", "_registry", "_program", "_key", "_calls",
                 "_compiles")

    def __init__(self, registry: "CompileRegistry", program: str,
                 fn, key):
        self._fn = fn
        self._registry = registry
        self._program = program
        self._key = key
        self._calls = 0
        self._compiles = 0

    def __call__(self, *args, **kwargs):
        n0, s0 = _probe()
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        dt = time.monotonic() - t0
        self._calls += 1
        if _PROBE_OK:
            n1, s1 = _probe()
            compiled = n1 > n0
            dt = s1 - s0  # actual backend-compile seconds, not wall time
        else:
            compiled = self._calls == 1
        if compiled:
            # Unexpected = THIS wrapper (one program instance, one
            # shape signature) compiling again AFTER warmup declared
            # steady state. Judged per-wrapper, not per registry key:
            # two runners in one process (tests, in-process
            # multi-worker launchers) each legitimately compile the
            # same (program, key) once. The warmup gate exists because
            # warmup itself intentionally double-compiles signatures
            # whose input shardings converge only after the first run
            # (e.g. the penalized window's counts under tp > 1).
            unexpected = (self._key is not None and self._compiles >= 1
                          and self._registry.warmup_complete)
            self._compiles += 1
            self._registry.note_compile(self._program, self._key, dt,
                                        unexpected=unexpected)
            self._registry.maybe_cost(self._program, self._fn, args, kwargs)
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


class CompileRegistry:
    """Process-wide compile observatory + per-window perf accumulator."""

    def __init__(self):
        self._lock = threading.Lock()  # compile bookkeeping only (rare)
        self._programs: dict[str, _Program] = {}
        self.warmup_complete = False
        self.warmup_complete_ts = 0.0
        # Per-window series (single engine-thread writer, lock-free).
        self.windows_total = 0
        self.window_seconds_total = 0.0
        self.window_tokens_total = 0
        self.step_seconds = 0.0        # EWMA seconds per decode step
        self.achieved_tok_s = 0.0      # EWMA tokens/s over device windows
        self.roofline_frac = 0.0       # EWMA achieved / weight-read roofline

    # -- compile observatory ---------------------------------------------------
    def wrap(self, program: str, fn, key=None) -> _InstrumentedJit:
        with self._lock:
            self._programs.setdefault(program, _Program(program))
        return _InstrumentedJit(self, program, fn, key)

    def note_compile(self, program: str, key, seconds: float,
                     unexpected: bool | None = None) -> None:
        """``key`` is the caller's shape-signature cache key. The
        instrumented wrapper passes ``unexpected`` explicitly (a second
        compile of the SAME wrapper — per program instance, so two
        runners in one process don't cross-flag); direct callers leave
        it None and the registry falls back to key-seen detection.
        ``key=None`` marks a self-bucketing program (one jit wrapper
        legitimately compiling per input shape — the multimodal
        encoders): compiles are counted but never flagged."""
        with self._lock:
            prog = self._programs.setdefault(program, _Program(program))
            seen = prog.sigs.get(key, 0)
            prog.sigs[key] = seen + 1
            prog.compiles += 1
            prog.compile_seconds += seconds
            prog.last_compile_ts = time.time()
            if unexpected is None:
                unexpected = key is not None and seen >= 1
            if unexpected:
                prog.unexpected += 1
        if unexpected:
            self._warn_recompile(program, key, seconds)

    def _warn_recompile(self, program: str, key, seconds: float) -> None:
        log.warning(
            "unexpected steady-state recompile: program %s key %r compiled "
            "again (%.3fs) — the jit cache for an already-served shape was "
            "invalidated (dtype/weak-type drift, donation mismatch, or a "
            "shape leak); decode pays XLA time on the hot path", program,
            key, seconds)
        from dynamo_tpu.runtime import tracing
        rec = tracing.get_recorder()
        if rec.enabled:
            now = time.monotonic()
            rec.add("perf.recompile", generate_trace_id(),
                    generate_span_id(), now - seconds, now, status="warn",
                    attrs={"program": program, "key": repr(key),
                           "compile_s": round(seconds, 4)})

    def maybe_cost(self, program: str, fn, args, kwargs) -> None:
        """One-time FLOPs/bytes estimate per program family. Cheap path
        (``lower().cost_analysis()``) traces but never XLA-compiles;
        the ``compile`` mode pays a real second compile for optimized
        numbers. Every failure is recorded, never raised — the perf
        plane must not be able to take down serving."""
        mode = _cost_mode()
        if mode == "off":
            return
        with self._lock:
            prog = self._programs.setdefault(program, _Program(program))
            if prog.cost is not None:
                return
            prog.cost = {"pending": True}  # claim before the slow work
        cost: dict
        try:
            lowered = fn.lower(*args, **kwargs)
            raw = (lowered.compile().cost_analysis() if mode == "compile"
                   else lowered.cost_analysis())
            if isinstance(raw, (list, tuple)):  # compiled returns per-device
                raw = raw[0] if raw else {}
            cost = {"flops": float(raw.get("flops", 0.0)),
                    "bytes_accessed": float(raw.get("bytes accessed", 0.0)),
                    "source": mode}
        except Exception as exc:  # noqa: BLE001 — backend-dependent API
            cost = {"error": f"{type(exc).__name__}: {exc}"[:200],
                    "source": mode}
        with self._lock:
            prog.cost = cost

    def mark_ready(self) -> None:
        """Warmup boundary: compiles recorded after this are post-warmup
        (the pane surfaces the flag; the recompile detector itself is
        per-signature and needs no boundary)."""
        self.warmup_complete = True
        self.warmup_complete_ts = time.time()

    # -- roofline-attributed window timing ------------------------------------
    def note_window(self, window_s: float, tokens: int, active: int,
                    steps: int, step_floor_ms: float) -> None:
        """One processed decode window (ENGINE THREAD: plain stores
        only). ``window_s`` is dispatch -> readback-complete device
        time, ``tokens`` the tokens it emitted, ``active`` the
        dispatched slot rows, ``step_floor_ms`` the shard's weight-read
        step floor (ModelSpec.weight_read_step_ms)."""
        if window_s <= 0 or steps <= 0:
            return
        self.windows_total += 1
        self.window_seconds_total += window_s
        self.window_tokens_total += tokens
        step_s = window_s / steps
        tok_s = tokens / window_s
        if self.windows_total == 1:
            self.step_seconds = step_s
            self.achieved_tok_s = tok_s
        else:
            self.step_seconds += _EWMA * (step_s - self.step_seconds)
            self.achieved_tok_s += _EWMA * (tok_s - self.achieved_tok_s)
        if active > 0 and step_floor_ms > 0:
            roofline_tok_s = active / (step_floor_ms / 1e3)
            frac = min(tok_s / roofline_tok_s, 1.0)
            if self.windows_total == 1:
                self.roofline_frac = frac
            else:
                self.roofline_frac += _EWMA * (frac - self.roofline_frac)

    # -- panes -----------------------------------------------------------------
    @property
    def compiles_total(self) -> int:
        return sum(p.compiles for p in self._programs.values())

    @property
    def unexpected_total(self) -> int:
        return sum(p.unexpected for p in self._programs.values())

    def snapshot(self) -> dict:
        """The /debug/perf "compiles" body."""
        with self._lock:
            programs = {
                name: {
                    "compiles": p.compiles,
                    "compile_seconds": round(p.compile_seconds, 4),
                    "signatures": len(p.sigs),
                    "unexpected_recompiles": p.unexpected,
                    "cost": p.cost,
                    "last_compile_ts": p.last_compile_ts,
                }
                for name, p in sorted(self._programs.items())
            }
        return {
            "programs": programs,
            "compiles_total": sum(v["compiles"] for v in programs.values()),
            "compile_seconds_total": round(
                sum(v["compile_seconds"] for v in programs.values()), 4),
            "unexpected_recompiles_total": sum(
                v["unexpected_recompiles"] for v in programs.values()),
            "warmup_complete": self.warmup_complete,
        }

    def window_snapshot(self) -> dict:
        """The /debug/perf "window" body (EWMA-smoothed live series)."""
        return {
            "windows_total": self.windows_total,
            "window_seconds_total": round(self.window_seconds_total, 4),
            "window_tokens_total": self.window_tokens_total,
            "step_seconds": self.step_seconds,
            "achieved_tok_per_s": round(self.achieved_tok_s, 2),
            "roofline_frac": round(self.roofline_frac, 4),
        }

    def reset(self) -> None:
        """Tests only: drop every program and window sample."""
        with self._lock:
            self._programs.clear()
        self.warmup_complete = False
        self.warmup_complete_ts = 0.0
        self.windows_total = 0
        self.window_seconds_total = 0.0
        self.window_tokens_total = 0
        self.step_seconds = 0.0
        self.achieved_tok_s = 0.0
        self.roofline_frac = 0.0


_REGISTRY = CompileRegistry()


def get_registry() -> CompileRegistry:
    return _REGISTRY


def instrumented_jit(program: str, fun, *, key=None, registry=None,
                     **jit_kwargs):
    """The ONE sanctioned way to build a serving-path jit program:
    ``jax.jit`` + compile observatory in a drop-in wrapper. ``program``
    is the family label (``prefill``, ``decode_window``, ...); ``key``
    the shape-signature cache key the caller memoizes under (the
    recompile detector treats a second compile of the same key as
    unexpected). Extra kwargs go straight to ``jax.jit``."""
    reg = registry if registry is not None else _REGISTRY
    # dtpu: ignore[jit-recompile-hazard] until=2027-08-01 -- this IS the caching chokepoint: every caller memoizes the returned wrapper by its shape key
    return reg.wrap(program, jax.jit(fun, **jit_kwargs), key=key)


def process_perf_status() -> dict:
    """Fallback /debug/perf body for a process without an engine (a
    frontend in proxy mode, a bare status server): the compile
    observatory is process-global, so it still answers."""
    reg = get_registry()
    return {"role": "process", "compiles": reg.snapshot(),
            "window": reg.window_snapshot(), "hbm": {}, "memory": {}}


class PerfMetricsUpdater:
    """dynamo_tpu_perf_* exporter: registry plain-ints -> Prometheus,
    throttled so the engine thread never takes a Prometheus lock per
    window (same pattern as KvMetricsUpdater). Counters export DELTAS
    so a registry reset can't make them go backwards. Every series is
    documented in docs/OBSERVABILITY.md "Engine perf plane" (tier-1
    docs-drift guard)."""

    def __init__(self, registry, min_interval_s: float = 0.5):
        self.min_interval_s = min_interval_s
        self._next = 0.0
        self._last: dict[tuple, float] = {}
        self.c_compiles = registry.counter(
            "perf_compiles_total", "XLA compiles per jit program family",
            ["program"])
        self.c_compile_seconds = registry.counter(
            "perf_compile_seconds_total", "Wall-clock seconds spent in XLA "
            "compiles per jit program family", ["program"])
        self.c_unexpected = registry.counter(
            "perf_unexpected_recompiles_total", "Compiles of an "
            "already-seen (program, signature) after first use — the "
            "runtime twin of the jit-recompile-hazard lint rule; any "
            "nonzero rate in steady state is a serving-path bug",
            ["program"])
        self.g_step_seconds = registry.gauge(
            "perf_step_seconds", "EWMA seconds per decode step "
            "(window device time / window steps)")
        self.g_achieved = registry.gauge(
            "perf_achieved_tok_per_s", "EWMA decode tokens/s over "
            "dispatched windows (device-time attributed)")
        self.g_roofline = registry.gauge(
            "perf_roofline_frac", "EWMA fraction of the shard's "
            "weight-read roofline achieved by decode windows")
        self.g_hbm_in_use = registry.gauge(
            "perf_hbm_bytes_in_use", "device.memory_stats bytes_in_use "
            "on this worker's first addressable device")
        self.g_hbm_peak = registry.gauge(
            "perf_hbm_peak_bytes", "device.memory_stats "
            "peak_bytes_in_use on this worker's first addressable device")
        self.g_hbm_limit = registry.gauge(
            "perf_hbm_limit_bytes", "device.memory_stats bytes_limit on "
            "this worker's first addressable device")
        self.c_spec_draft_tokens = registry.counter(
            "perf_spec_draft_tokens_total", "Speculative draft tokens "
            "proposed by the on-device n-gram drafter")
        self.c_spec_accepted_tokens = registry.counter(
            "perf_spec_accepted_tokens_total", "Speculative draft tokens "
            "accepted by the fused verify (rejection-sampled for "
            "temperature > 0; exact-match under greedy)")
        self.c_spec_verify_steps = registry.counter(
            "perf_spec_verify_steps_total", "Speculative verify steps by "
            "tokens emitted — the per-window emitted-token histogram "
            "(emitted=1 means no draft accepted; emitted=spec_k+1 means "
            "the whole draft block landed; emitted=0 a frozen slot)",
            ["emitted"])
        self.g_spec_acceptance = registry.gauge(
            "perf_spec_acceptance_rate", "Lifetime accepted/proposed "
            "draft-token ratio of the speculative verify")
        self.c_spec_brownout = registry.counter(
            "perf_spec_brownout_windows_total", "Decode windows where "
            "brownout pressure suspended speculative drafting")
        for bound in (self.g_step_seconds, self.g_achieved, self.g_roofline,
                      self.g_hbm_in_use, self.g_hbm_peak, self.g_hbm_limit):
            bound.ensure()

    def _delta(self, bound, key: tuple, current: float, **labels) -> None:
        prev = self._last.get(key, 0.0)
        if current > prev:
            bound.inc(current - prev, **labels)
        self._last[key] = current

    def update(self, engine, force: bool = False) -> None:
        """``engine`` duck-types TPUEngine: needs ``.runner.hbm_stats``
        (optional). Throttled; safe from the engine thread."""
        now = time.monotonic()
        if not force and now < self._next:
            return
        self._next = now + self.min_interval_s
        reg = get_registry()
        with reg._lock:
            per_prog = [(p.name, p.compiles, p.compile_seconds, p.unexpected)
                        for p in reg._programs.values()]
        for name, compiles, seconds, unexpected in per_prog:
            self._delta(self.c_compiles, ("c", name), compiles, program=name)
            self._delta(self.c_compile_seconds, ("s", name), seconds,
                        program=name)
            self._delta(self.c_unexpected, ("u", name), unexpected,
                        program=name)
        self.g_step_seconds.set(reg.step_seconds)
        self.g_achieved.set(reg.achieved_tok_s)
        self.g_roofline.set(reg.roofline_frac)
        runner = getattr(engine, "runner", None)
        hbm = runner.hbm_stats() if runner is not None and hasattr(
            runner, "hbm_stats") else {}
        if hbm:
            self.g_hbm_in_use.set(hbm.get("bytes_in_use", 0))
            self.g_hbm_peak.set(hbm.get("peak_bytes_in_use", 0))
            self.g_hbm_limit.set(hbm.get("bytes_limit", 0))
        if getattr(engine, "spec_emit_hist", None):
            self._delta(self.c_spec_draft_tokens, ("spec_dt",),
                        engine.spec_tokens)
            self._delta(self.c_spec_accepted_tokens, ("spec_at",),
                        engine.spec_accepted)
            self._delta(self.c_spec_brownout, ("spec_bw",),
                        engine.spec_brownout_windows)
            for e, n in enumerate(engine.spec_emit_hist):
                self._delta(self.c_spec_verify_steps, ("spec_eh", e), n,
                            emitted=str(e))
            if engine.spec_tokens:
                self.g_spec_acceptance.set(
                    engine.spec_accepted / engine.spec_tokens)
