"""Pallas TPU paged-attention decode kernel.

The hot op of the decode step (the role block_copy.cu + engine attention
kernels play on the reference's GPUs). One grid program per (sequence,
kv-head): it walks the sequence's page table (scalar-prefetched into SMEM),
DMAs K/V pages HBM->VMEM in double-buffered chunks of PAGES_PER_CHUNK pages,
and accumulates flash-style online softmax for the q_per_kv grouped query
heads. Only live pages are read — unlike the XLA gather fallback
(model.paged_decode_attention_xla) which touches max_len for every sequence.

Layout contract: k_pages/v_pages are [Nkv, P, page_size, head_dim] so one
(head, page) slab [page_size, head_dim] is contiguous for DMA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PAGES_PER_CHUNK = 8  # tokens per chunk = 8 * page_size (128 for 16-tok pages)
NEG_INF = -1e30


class _ChunkCopy:
    """Async copy of PAGES_PER_CHUNK K/V pages for one (head, chunk) into a
    VMEM slot (idiom after the stock multi-page copy descriptor)."""

    def __init__(self, hbm_ref, buf, sem, page_table_ref, b, h, chunk,
                 max_pages):
        self._copies = []
        for j in range(PAGES_PER_CHUNK):
            idx = jnp.minimum(chunk * PAGES_PER_CHUNK + j, max_pages - 1)
            pid = page_table_ref[b, idx]
            self._copies.append(pltpu.make_async_copy(
                hbm_ref.at[h].at[pid], buf.at[j], sem))

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


def _decode_kernel(page_table_ref, seq_lens_ref,  # scalar prefetch (SMEM)
                   q_ref, k_hbm, v_hbm,  # q VMEM block; k/v full arrays (ANY)
                   out_ref,  # output VMEM block
                   k_buf, v_buf, sems,  # scratch
                   *, page_size: int, max_pages: int):
    b = pl.program_id(0)
    h = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    chunk_tokens = PAGES_PER_CHUNK * page_size
    num_chunks = jnp.maximum(1, pl.cdiv(seq_len, chunk_tokens))

    qpk = q_ref.shape[2]
    d = q_ref.shape[3]
    q = q_ref[0, 0].astype(jnp.float32)  # [qpk, D]
    scale = 1.0 / (d ** 0.5)

    def make_copies(c, slot):
        kc = _ChunkCopy(k_hbm, k_buf.at[slot], sems.at[0, slot],
                        page_table_ref, b, h, c, max_pages)
        vc = _ChunkCopy(v_hbm, v_buf.at[slot], sems.at[1, slot],
                        page_table_ref, b, h, c, max_pages)
        return kc, vc

    kc0, vc0 = make_copies(0, 0)
    kc0.start()
    vc0.start()

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            kc, vc = make_copies(c + 1, jax.lax.rem(c + 1, 2))
            kc.start()
            vc.start()

        kc, vc = make_copies(c, slot)
        kc.wait()
        vc.wait()
        k = k_buf[slot].astype(jnp.float32).reshape(chunk_tokens, d)
        v = v_buf[slot].astype(jnp.float32).reshape(chunk_tokens, d)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [qpk, chunk]
        token_idx = (c * chunk_tokens
                     + jax.lax.broadcasted_iota(jnp.int32,
                                                (qpk, chunk_tokens), 1))
        scores = jnp.where(token_idx < seq_len, scores, NEG_INF)
        # Online softmax update.
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((qpk, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((qpk, 1), jnp.float32)
    acc0 = jnp.zeros((qpk, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[0, 0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("q_per_kv",))
def paged_decode_attention_pallas(q: jax.Array, k_pages: jax.Array,
                                  v_pages: jax.Array, page_table: jax.Array,
                                  seq_lens: jax.Array, q_per_kv: int
                                  ) -> jax.Array:
    """Drop-in replacement for model.paged_decode_attention_xla.

    q [B,Nh,D]; k_pages/v_pages [Nkv,P,page,D]; page_table [B,maxP];
    seq_lens [B]. Returns [B,Nh,D].
    """
    b, nh, d = q.shape
    nkv, _, page_size, _ = k_pages.shape
    maxp = page_table.shape[1]
    qg = q.reshape(b, nkv, q_per_kv, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nkv),
        in_specs=[
            pl.BlockSpec((1, 1, q_per_kv, d), lambda i, j, *_: (i, j, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, q_per_kv, d),
                               lambda i, j, *_: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, PAGES_PER_CHUNK, page_size, d), k_pages.dtype),
            pltpu.VMEM((2, PAGES_PER_CHUNK, page_size, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               max_pages=maxp)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, q_per_kv, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(page_table, seq_lens, qg, k_pages, v_pages)
    return out.reshape(b, nh, d)
