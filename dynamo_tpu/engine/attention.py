"""Pallas TPU paged-attention decode kernel.

The hot op of the decode step (the role block_copy.cu + engine attention
kernels play on the reference's GPUs). One grid program per (sequence,
kv-head): it walks the sequence's page table (scalar-prefetched into SMEM),
DMAs K/V pages HBM->VMEM in double-buffered chunks of PAGES_PER_CHUNK pages,
and accumulates flash-style online softmax for the q_per_kv grouped query
heads. Only live pages are read — unlike the XLA gather fallback
(model.paged_decode_attention_xla) which touches max_len for every sequence.

Lane packing: Mosaic DMAs want the trailing dim = 128 lanes, but head_dim 64
models (qwen2.5-0.5b etc.) have 64-wide K/V rows. The kernel therefore views
each page as [page_size*D/128, 128] — for D=64 each 128-lane row packs
tpr=2 consecutive tokens — and runs the flash accumulation in packed space:

- queries are pre-expanded to q2 [tpr*qpk, 128] where group t occupies lanes
  [t*D,(t+1)*D) (so dot(q2, K2^T) yields group t's scores against packed
  rows, i.e. tokens r*tpr+t);
- each packed row keeps its own (m, l, acc) flash stats — no cross-group
  communication inside the kernel (Mosaic relayouts across sublane groups
  are fragile); the kernel emits unnormalized acc plus m and l;
- the wrapper merges the tpr groups per head in XLA (standard flash merge:
  rescale by exp(m_t - m*), sum, divide by combined l) and sums the
  per-group lane windows.

For D >= 128 this degenerates (tpr=1) to the natural unpacked layout with
the same merge doing only the final normalization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.engine.kv_quant import QuantKV

PAGES_PER_CHUNK = 8  # tokens per chunk = 8 * page_size (128 for 16-tok pages)
NEG_INF = -1e30

# jax renamed pltpu.TPUCompilerParams -> CompilerParams across releases;
# accept either so the kernel imports on every toolchain the repo targets.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


class _ChunkCopy:
    """Async copy of PAGES_PER_CHUNK K/V pages for one (layer, head, chunk)
    into a VMEM slot (idiom after the stock multi-page copy descriptor)."""

    def __init__(self, hbm_ref, buf, sem, layer, page_table_ref, b, h, chunk,
                 max_pages):
        self._copies = []
        for j in range(PAGES_PER_CHUNK):
            idx = jnp.minimum(chunk * PAGES_PER_CHUNK + j, max_pages - 1)
            pid = page_table_ref[b, idx]
            self._copies.append(pltpu.make_async_copy(
                hbm_ref.at[layer].at[h].at[pid], buf.at[j], sem))

    def start(self):
        for c in self._copies:
            c.start()

    def wait(self):
        for c in self._copies:
            c.wait()


def _decode_kernel(layer_ref, page_table_ref, seq_lens_ref,  # SMEM prefetch
                   q_ref, k_hbm, v_hbm,  # q2 VMEM block; k/v packed (ANY)
                   *rest,  # [ks_hbm, vs_hbm if quantized], outputs, scratch
                   page_size: int, max_pages: int, tpr: int, qpk: int,
                   quantized: bool = False):
    if quantized:
        # int8 pages + per-token f32 scale rows ([L, Nkv, P, page] in
        # HBM): the scale chunks ride their own DMAs beside the pages and
        # dequantization happens in-register below — no bf16 copy of the
        # history is ever materialized.
        (ks_hbm, vs_hbm, acc_ref, m_ref, l_ref,
         k_buf, v_buf, ks_buf, vs_buf, sems) = rest
    else:
        acc_ref, m_ref, l_ref, k_buf, v_buf, sems = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    layer = layer_ref[0]
    seq_len = seq_lens_ref[b]
    chunk_tokens = PAGES_PER_CHUNK * page_size
    rows = chunk_tokens // tpr  # packed rows per chunk
    num_chunks = jnp.maximum(1, pl.cdiv(seq_len, chunk_tokens))

    n = tpr * qpk
    q2 = q_ref[0, 0].astype(jnp.float32)  # [n, 128]
    d = 128 // tpr
    scale = 1.0 / (d ** 0.5)

    def make_copies(c, slot):
        copies = [
            _ChunkCopy(k_hbm, k_buf.at[slot], sems.at[0, slot], layer,
                       page_table_ref, b, h, c, max_pages),
            _ChunkCopy(v_hbm, v_buf.at[slot], sems.at[1, slot], layer,
                       page_table_ref, b, h, c, max_pages)]
        if quantized:
            copies.append(_ChunkCopy(ks_hbm, ks_buf.at[slot],
                                     sems.at[2, slot], layer,
                                     page_table_ref, b, h, c, max_pages))
            copies.append(_ChunkCopy(vs_hbm, vs_buf.at[slot],
                                     sems.at[3, slot], layer,
                                     page_table_ref, b, h, c, max_pages))
        return copies

    for cp in make_copies(0, 0):
        cp.start()

    # token index of (row-group t, packed row r) is chunk_start + r*tpr + t
    # where t = sublane // qpk.
    group = jax.lax.broadcasted_iota(jnp.int32, (n, rows), 0) // qpk
    row = jax.lax.broadcasted_iota(jnp.int32, (n, rows), 1)

    def dequant_expand(sbuf_slot):
        # Scale chunk [PAGES_PER_CHUNK, page_size] -> lane-expanded
        # [rows, 128]: packed row r lane-group t holds token r*tpr+t, so
        # its scale is flat[r*tpr+t] = reshape(rows, tpr)[r, t]. The
        # [rows, 1] -> [rows, 128] lane broadcast per group keeps the
        # expansion Mosaic-friendly (no cross-sublane relayout).
        s2 = sbuf_slot.reshape(rows, tpr)
        lane_t = jax.lax.broadcasted_iota(jnp.int32, (rows, 128), 1) // d
        out = jnp.zeros((rows, 128), jnp.float32)
        for t in range(tpr):
            out = out + jnp.where(
                lane_t == t,
                jnp.broadcast_to(s2[:, t:t + 1], (rows, 128)), 0.0)
        return out

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            for cp in make_copies(c + 1, jax.lax.rem(c + 1, 2)):
                cp.start()

        for cp in make_copies(c, slot):
            cp.wait()
        k2 = k_buf[slot].astype(jnp.float32).reshape(rows, 128)
        v2 = v_buf[slot].astype(jnp.float32).reshape(rows, 128)
        if quantized:
            k2 = k2 * dequant_expand(ks_buf[slot])
            v2 = v2 * dequant_expand(vs_buf[slot])
        scores = jax.lax.dot_general(
            q2, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [n, rows]
        token_idx = c * chunk_tokens + row * tpr + group
        scores = jnp.where(token_idx < seq_len, scores, NEG_INF)
        # Per-row online softmax (groups merged outside the kernel).
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((n, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n, 1), jnp.float32)
    acc0 = jnp.zeros((n, 128), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))
    acc_ref[0, 0] = acc.astype(acc_ref.dtype)
    m_ref[0, 0] = jnp.broadcast_to(m, (n, 128))
    l_ref[0, 0] = jnp.broadcast_to(l, (n, 128))


def _hist_flash_pallas(q, k_cache, v_cache, layer, page_table, hist_lens,
                       q_per_kv):
    """Run the kernel over the cache-resident history; returns the flash
    triple (num [b,nkv,qpk,d] unnormalized, l_star [b,nkv,qpk,1],
    m_s [b,nkv,qpk,1]) for the wrapper to merge with out-of-cache columns
    (the in-window buffer and/or the current token)."""
    b, nh, d = q.shape
    _, nkv, num_pages, page_size, _ = k_cache.shape
    maxp = page_table.shape[1]
    seq_lens = hist_lens
    q_per_kv = int(q_per_kv)
    if d >= 128:
        # The packed-row math assumes one token per 128-lane row; d > 128
        # would need a multi-row-per-token variant (no current model needs
        # it: Llama/Qwen/Mistral families are all D=64 or D=128).
        assert d == 128, f"head_dim {d} > 128 unsupported by this kernel"
        tpr = 1
    else:
        assert 128 % d == 0 and (page_size * d) % 128 == 0, (
            f"head_dim {d} cannot pack into 128 lanes")
        tpr = 128 // d
    qpk = q_per_kv
    n = tpr * qpk
    rows_per_page = page_size * d // 128

    # Pack the caches: view each page as [rows_per_page, 128] (zero-cost
    # reshape: same row-major layout). int8 pools (QuantKV) pack their
    # data pages the same way and additionally ship the per-token scale
    # rows; the kernel dequantizes in-register after the HBM->VMEM DMA.
    quantized = isinstance(k_cache, QuantKV)
    L = k_cache.shape[0]
    k_pages = k_cache.data if quantized else k_cache
    v_pages = v_cache.data if quantized else v_cache
    kp = k_pages.reshape(L, nkv, num_pages, rows_per_page, 128)
    vp = v_pages.reshape(L, nkv, num_pages, rows_per_page, 128)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)

    # Expand q: group t occupies rows [t*qpk,(t+1)*qpk) and lanes
    # [t*d,(t+1)*d).
    qg = q.reshape(b, nkv, qpk, d)
    if tpr == 1:
        q2 = qg
    else:
        q2 = jnp.zeros((b, nkv, n, 128), q.dtype)
        for t in range(tpr):
            q2 = q2.at[:, :, t * qpk:(t + 1) * qpk, t * d:(t + 1) * d].set(qg)

    blk = pl.BlockSpec((1, 1, n, tpr * d), lambda i, j, *_: (i, j, 0, 0))
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    in_specs = [blk, any_spec, any_spec]
    operands = [q2, kp, vp]
    scratch = [
        pltpu.VMEM((2, PAGES_PER_CHUNK, rows_per_page, 128), kp.dtype),
        pltpu.VMEM((2, PAGES_PER_CHUNK, rows_per_page, 128), vp.dtype),
    ]
    if quantized:
        # Scale rows [L, Nkv, P, page] ride their own chunk DMAs; the
        # extra semaphore pairs below fence them independently.
        in_specs += [any_spec, any_spec]
        operands += [k_cache.scale, v_cache.scale]
        scratch += [
            pltpu.VMEM((2, PAGES_PER_CHUNK, page_size), jnp.float32),
            pltpu.VMEM((2, PAGES_PER_CHUNK, page_size), jnp.float32),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((4 if quantized else 2, 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, nkv),
        in_specs=in_specs,
        out_specs=(blk, blk, blk),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               max_pages=maxp, tpr=tpr, qpk=qpk,
                               quantized=quantized)
    shape = jax.ShapeDtypeStruct((b, nkv, n, tpr * d), jnp.float32)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(shape, shape, shape),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        # CPU (CI / the virtual test mesh) runs the TPU kernel through the
        # Pallas interpreter; Mosaic compiles it on real chips.
        interpret=jax.default_backend() == "cpu",
    )(layer_arr, page_table, seq_lens, *operands)
    m = m[..., :1]  # broadcast lanes -> scalar stat per row
    l = l[..., :1]
    if tpr == 1:
        num = acc.reshape(b, nkv, qpk, d)
        l_star = l.reshape(b, nkv, qpk, 1)
        m_s = m.reshape(b, nkv, qpk, 1)
    else:
        # Flash-merge the tpr groups of each head, then sum each group's
        # valid lane window.
        acc4 = acc.reshape(b, nkv, tpr, qpk, 128)
        m4 = m.reshape(b, nkv, tpr, qpk, 1)
        l4 = l.reshape(b, nkv, tpr, qpk, 1)
        m_star = jnp.max(m4, axis=2, keepdims=True)
        w = jnp.exp(m4 - m_star)
        l_star = jnp.sum(w * l4, axis=2)  # [b,nkv,qpk,1]
        num = sum((w[:, :, t] * acc4[:, :, t])[..., t * d:(t + 1) * d]
                  for t in range(tpr))  # [b,nkv,qpk,d]
        m_s = m_star.reshape(b, nkv, qpk, 1)
    return num, l_star, m_s


def _merge_extra(q, num, l_star, m_s, k_extra, v_extra, s_mask, q_per_kv):
    """Flash-merge the kernel's history block with explicit extra columns
    (window buffer tokens and/or the current token). k_extra/v_extra
    [b,nkv,J,d]; s_mask [b,1,1,J] bool (True = valid)."""
    b, nh, d = q.shape
    nkv = k_extra.shape[1]
    qpk = q_per_kv
    qg = q.reshape(b, nkv, qpk, d).astype(jnp.float32)
    s = jnp.einsum("bngd,bnjd->bngj", qg,
                   k_extra.astype(jnp.float32)) / (d ** 0.5)
    s = jnp.where(s_mask, s, NEG_INF)
    m_b = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m_b)
    l_b = jnp.sum(p, axis=-1, keepdims=True)
    acc_b = jnp.einsum("bngj,bnjd->bngd", p, v_extra.astype(jnp.float32))
    m_t = jnp.maximum(m_s, m_b)
    w_h = jnp.exp(m_s - m_t)
    w_b = jnp.exp(m_b - m_t)
    out = ((num * w_h + acc_b * w_b)
           / jnp.maximum(l_star * w_h + l_b * w_b, 1e-30))
    return out.astype(q.dtype).reshape(b, nh, d)


# dtpu: ignore[unregistered-jit] -- inner kernel: only ever traced INSIDE registered runner programs (inlined), never dispatched standalone from the serving loop
@functools.partial(jax.jit, static_argnames=("q_per_kv",))
def paged_decode_attention_pallas(q: jax.Array, k_cache: jax.Array,
                                  v_cache: jax.Array, layer: jax.Array,
                                  page_table: jax.Array, hist_lens: jax.Array,
                                  k_self: jax.Array, v_self: jax.Array,
                                  q_per_kv: int) -> jax.Array:
    """Drop-in replacement for model.paged_decode_attention_xla.

    q [B,Nh,D]; k_cache/v_cache [L,Nkv,P,page,D] (the FULL stacked cache —
    the kernel DMAs pages of the given layer directly, never slicing);
    layer: scalar layer index; page_table [B,maxP]; hist_lens [B] (tokens
    already cache-resident); k_self/v_self [B,Nkv,D] (the new token's K/V,
    merged as an extra flash column outside the kernel). Returns [B,Nh,D].
    Requires page_size*D % 128 == 0 and 128 % D == 0 (packed) or
    D % 128 == 0 (natural).
    """
    b = q.shape[0]
    nkv = k_cache.shape[1]
    num, l_star, m_s = _hist_flash_pallas(q, k_cache, v_cache, layer,
                                          page_table, hist_lens, q_per_kv)
    mask = jnp.ones((b, 1, 1, 1), bool)
    return _merge_extra(q, num, l_star, m_s, k_self[:, :, None, :],
                        v_self[:, :, None, :], mask, q_per_kv)


# dtpu: ignore[unregistered-jit] -- inner kernel: only ever traced INSIDE registered runner programs (inlined), never dispatched standalone from the serving loop
@functools.partial(jax.jit, static_argnames=("q_per_kv",))
def paged_window_attention_pallas(q: jax.Array, k_cache: jax.Array,
                                  v_cache: jax.Array, layer: jax.Array,
                                  page_table: jax.Array, hist_lens: jax.Array,
                                  k_win: jax.Array, v_win: jax.Array,
                                  m: jax.Array, k_self: jax.Array,
                                  v_self: jax.Array, q_per_kv: int
                                  ) -> jax.Array:
    """Window variant (model.paged_window_attention_xla interface): kernel
    over the cache-resident history + XLA flash-merge of the in-window
    buffer (cols j < m) and the current token. k_win/v_win [Nkv,B,M,D]."""
    b = q.shape[0]
    M = k_win.shape[2]
    num, l_star, m_s = _hist_flash_pallas(q, k_cache, v_cache, layer,
                                          page_table, hist_lens, q_per_kv)
    k_extra = jnp.concatenate(
        [k_win.transpose(1, 0, 2, 3), k_self[:, :, None, :]], axis=2)
    v_extra = jnp.concatenate(
        [v_win.transpose(1, 0, 2, 3), v_self[:, :, None, :]], axis=2)
    win_valid = jnp.arange(M)[None, :] < m          # [1,M] (m traced)
    col_mask = jnp.concatenate(
        [jnp.broadcast_to(win_valid, (b, M)),
         jnp.ones((b, 1), bool)], axis=1)[:, None, None, :]
    return _merge_extra(q, num, l_star, m_s, k_extra, v_extra, col_mask,
                        q_per_kv)
