"""Paged KV cache: device arrays + host-side page allocator with prefix reuse.

The device side is two arrays per model: k/v pages
[layers, num_pages, page_size, kv_heads, head_dim] sharded over "tp" on the
kv_heads axis. The host side is the page allocator — the in-HBM (G1) tier of
the reference's KVBM block lifecycle (lib/llm/src/block_manager: active pool /
inactive reusable pool / LRU eviction): pages of finished sequences stay
registered under their chained block hash and are reused on prefix hits until
evicted. Emits stored/removed block hashes for the router's index.

Lifecycle invariant (reference block_manager/pool/managed.rs): a page is
either FREE (unregistered, refcount 0), ACTIVE (refcount > 0 — held by one
or more live sequences; may also be registered for sharing), or INACTIVE
(registered, refcount 0 — reusable on a prefix hit, evictable LRU).
Only INACTIVE pages may be evicted: evicting a page a live sequence still
writes to would silently corrupt its KV.
"""

from __future__ import annotations

from collections import OrderedDict

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kv_cache")


class PageAllocator:
    # Page 0 is RESERVED as the scratch page: inactive decode slots have
    # all-zero page tables, so their dummy K/V scatters land there instead of
    # clobbering live data. Never allocated.
    SCRATCH_PAGE = 0

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages - 1  # page 0 reserved
        self.page_size = page_size
        self.free: list[int] = list(range(num_pages - 1, 0, -1))
        # All registered blocks: block_hash -> page id.
        self.cached: dict[int, int] = {}
        self.cached_by_page: dict[int, int] = {}
        # INACTIVE subset (registered AND refcount 0) in LRU order — the
        # only pages eviction may take.
        self.inactive: OrderedDict[int, int] = OrderedDict()
        # Active references: page id -> refcount.
        self.refs: dict[int, int] = {}
        # Router event buffers.
        self.stored_events: list[int] = []
        self.removed_events: list[int] = []
        # Telemetry (plain ints: engine-thread hot path; exported as
        # dynamo_tpu_kv_* by engine/kv_metrics.py, docs/OBSERVABILITY.md
        # "KV & capacity").
        self.reuse_hit_blocks = 0      # cached pages pinned on prefix hits
        self.reuse_lookup_blocks = 0   # blocks probed by acquire_cached
        self.evicted_blocks = 0        # LRU evictions under allocation
        self.demoted_blocks = 0        # proactive watermark demotions (KVBM)
        self.cleared_blocks = 0        # pages reclaimed by clear_inactive
        self.clear_inactive_calls = 0
        # Offload hook (G2 tiering): called as hook(block_hash, page) when
        # an inactive registered page is evicted, BEFORE the page can be
        # handed out — the engine schedules a device->host extract so the
        # block survives in the host tier.
        self.evict_hook = None

    # -- queries --------------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self.free) + len(self.inactive)

    @property
    def num_active(self) -> int:
        return len(self.refs)

    def lookup(self, block_hashes: list[int]) -> list[int]:
        """Page ids for the longest cached prefix of ``block_hashes``."""
        pages = []
        for h in block_hashes:
            page = self.cached.get(h)
            if page is None:
                break
            pages.append(page)
        return pages

    # -- allocation -----------------------------------------------------------
    def allocate(self, count: int) -> list[int] | None:
        """Allocate ``count`` fresh pages (evicting LRU *inactive* cached
        pages as needed — never a page a live sequence holds). None if
        impossible."""
        if self.num_free < count:
            return None
        out = []
        for _ in range(count):
            if self.free:
                page = self.free.pop()
            else:
                # Evict least-recently-used inactive page.
                h, page = self.inactive.popitem(last=False)
                del self.cached[h]
                del self.cached_by_page[page]
                self.removed_events.append(h)
                self.evicted_blocks += 1
                if self.evict_hook is not None:
                    self.evict_hook(h, page)
            assert page not in self.refs, \
                f"allocator invariant violated: page {page} already active"
            self.refs[page] = 1
            out.append(page)
        return out

    def acquire_cached(self, block_hashes: list[int]) -> list[int]:
        """Pin the cached prefix pages for reuse; returns their page ids."""
        pages = []
        self.reuse_lookup_blocks += len(block_hashes)
        for h in block_hashes:
            page = self.cached.get(h)
            if page is None:
                break
            self.reuse_hit_blocks += 1
            # Inactive -> active (stays registered so other sequences can
            # share — refcount tracks active users).
            self.inactive.pop(h, None)
            self.refs[page] = self.refs.get(page, 0) + 1
            pages.append(page)
        return pages

    def register(self, page: int, block_hash: int) -> None:
        """A page now holds a COMPLETE block: make it reusable by hash
        (reference block lifecycle Complete->Registered, block_manager
        block.rs)."""
        existing = self.cached_by_page.get(page)
        if existing == block_hash:
            return
        if existing is not None:
            # The page's content no longer matches its old hash: drop the
            # stale registration entirely.
            del self.cached_by_page[page]
            self.cached.pop(existing, None)
            self.inactive.pop(existing, None)
            self.removed_events.append(existing)
        if block_hash in self.cached:
            # Another page already holds this block; keep the older one. A
            # page whose old registration we just dropped must not leak out
            # of every pool: unreferenced -> back to free.
            if existing is not None and page not in self.refs:
                self.free.append(page)
            return
        self.cached[block_hash] = page
        self.cached_by_page[page] = block_hash
        if page not in self.refs:
            self.inactive[block_hash] = page
        self.stored_events.append(block_hash)

    def unregister(self, pages: list[int]) -> None:
        """Drop these pages' prefix-cache registrations (used when a request
        fails and its KV contents must not be reused)."""
        for page in pages:
            h = self.cached_by_page.pop(page, None)
            if h is not None:
                self.cached.pop(h, None)
                self.inactive.pop(h, None)
                self.removed_events.append(h)
                if page not in self.refs:
                    self.free.append(page)

    def release(self, pages: list[int]) -> None:
        """Drop one active reference; unreferenced unregistered pages return
        to the free list, registered ones become inactive (reusable LRU,
        most-recently-released last)."""
        for page in pages:
            ref = self.refs.get(page)
            if ref is None:
                continue
            if ref > 1:
                self.refs[page] = ref - 1
                continue
            del self.refs[page]
            h = self.cached_by_page.get(page)
            if h is None:
                self.free.append(page)
            else:
                self.inactive[h] = page

    def demote_lru(self, count: int,
                   skip: frozenset | set = frozenset()) -> list[int]:
        """Proactively demote up to ``count`` LRU *inactive* blocks out of
        HBM (the KVBM watermark sweep, engine/kvbm.py): the pages return
        to the free list and the evict hook offloads their contents to
        the host tier, exactly like allocation-pressure eviction — but
        BEFORE an allocation burst has to pay the evict+extract ordering.
        Hashes in ``skip`` (the KVBM pin set) and ACTIVE pages are never
        taken. Returns the demoted block hashes."""
        out: list[int] = []
        for h in list(self.inactive):
            if len(out) >= count:
                break
            if h in skip:
                continue
            page = self.inactive.pop(h)
            del self.cached[h]
            del self.cached_by_page[page]
            self.removed_events.append(h)
            self.demoted_blocks += 1
            if self.evict_hook is not None:
                self.evict_hook(h, page)
            self.free.append(page)
            out.append(h)
        return out

    def clear_inactive(self) -> int:
        """Drop every INACTIVE prefix-cache registration (pages held by
        live sequences are untouched) — the reference's clear_kv_blocks
        admin operation. Returns the number of pages freed."""
        n = 0
        for h, page in list(self.inactive.items()):
            del self.inactive[h]
            self.cached.pop(h, None)
            self.cached_by_page.pop(page, None)
            self.removed_events.append(h)
            self.free.append(page)
            n += 1
        self.clear_inactive_calls += 1
        self.cleared_blocks += n
        return n

    def stats(self) -> dict:
        """Occupancy + lifecycle counters for /debug/kv and the
        dynamo_tpu_kv_* exporters (engine/kv_metrics.py)."""
        return {
            "pages_total": self.num_pages,
            "pages_free": len(self.free),
            "pages_active": len(self.refs),
            "pages_inactive": len(self.inactive),
            "cached_blocks": len(self.cached),
            "occupancy": (len(self.refs) / self.num_pages
                          if self.num_pages else 0.0),
            "reuse_hit_blocks": self.reuse_hit_blocks,
            "reuse_lookup_blocks": self.reuse_lookup_blocks,
            "evicted_blocks": self.evicted_blocks,
            "demoted_blocks": self.demoted_blocks,
            "cleared_blocks": self.cleared_blocks,
            "clear_inactive_calls": self.clear_inactive_calls,
        }

    def drain_events(self) -> tuple[list[int], list[int]]:
        stored, self.stored_events = self.stored_events, []
        removed, self.removed_events = self.removed_events, []
        return stored, removed
