"""Weight-only int8 quantization with bf16 compute.

The decode hot path is HBM-bandwidth-bound (one full weight read per
step — docs/PERF_NOTES.md roofline), so halving weight bytes both
doubles the decode ceiling and is what fits full Llama-3-8B (16 GB bf16)
on a single 16 GB v5e chip beside its KV cache (round-3 VERDICT missing
#7; the reference ecosystem's own baseline workload is a quantized 70B,
benchmarks/llm/perf.sh:18-29).

Scheme: symmetric per-output-channel int8. A weight W[..., in, out]
stores q = round(W/s) in int8 and s[..., 1, out] in float32;
matmuls run x @ q (int8 operand converted to bf16 in the dot — XLA
fuses the convert into the operand read, so the dequantized matrix is
never materialized) and the [out]-shaped scale multiplies the OUTPUT —
the standard weight-only pattern, MXU stays in bf16.

The embedding table quantizes per-hidden-channel: the token gather reads
int8 rows and scales [H]; the tied LM head contracts over H, so its
scale folds into the activation side ((x*s) @ q.T) — again no
materialized dequant.

QTensor is a NamedTuple, hence a pytree: scan-over-layers slicing,
sharding trees, and device placement all compose without special cases.
Router gates, norms and biases stay bf16 (tiny, accuracy-sensitive).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np


class QTensor(NamedTuple):
    """int8 weight + broadcastable scale; a pytree of two leaves."""
    q: Any   # int8 [..., in, out]
    s: Any   # float32 [..., 1, out]


# Layer leaves that quantize (the big matmuls); everything else stays bf16.
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "moe_w_gate", "moe_w_up", "moe_w_down")


def _safe_scale(amax: np.ndarray) -> np.ndarray:
    """amax/127 with two guards: all-zero channels take s=1 (exact
    round trip), and channels near float32-max step s DOWN one ulp when
    the division rounded up — otherwise the saturated code dequantizes
    to 127*s = inf (caught by the max-magnitude edge-case test)."""
    s = (amax / 127.0).astype(np.float32)
    s = np.where(s == 0.0, np.float32(1.0), s)
    with np.errstate(over="ignore"):
        over = ~np.isfinite(np.float32(127.0) * s)
    return np.where(over, np.nextafter(s, np.float32(0.0)), s)


def quantize_weight(w: np.ndarray) -> QTensor:
    """Symmetric per-out-channel int8 over the last axis (reduce over the
    contraction axis -2). Host-side, float32 math."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    s = _safe_scale(amax)
    q = np.clip(np.rint(wf / s), -127, 127).astype(np.int8)
    return QTensor(q=q, s=s)


def quantize_embedding(w: np.ndarray) -> QTensor:
    """Embedding table [V, H]: per-H-channel scale [1, H] — right for both
    the row gather (scale broadcasts over gathered rows) and the tied head
    (scale folds into the activations before the contraction)."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=0, keepdims=True)
    s = _safe_scale(amax)
    q = np.clip(np.rint(wf / s), -127, 127).astype(np.int8)
    return QTensor(q=q, s=s)


def quantize_params(params: dict) -> dict:
    """bf16 param pytree -> same tree with QTensor leaves for the big
    matmuls. Operates leaf-by-leaf so peak host memory stays ~one tensor
    above the input tree."""
    layers = dict(params["layers"])
    for key in QUANT_LAYER_KEYS:
        if key in layers:
            layers[key] = quantize_weight(layers[key])
    out = dict(params)
    out["layers"] = layers
    out["embed"] = quantize_embedding(params["embed"])
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def weight_dtype_bytes(quant: str | None) -> float:
    """Bytes per weight element for capacity/roofline accounting."""
    return 1.0 if quant == "int8" else 2.0


def random_params_for_timing(spec, seed: int = 7, scale: float = 1.0):
    """Build a (quantized, if spec.quant) param tree with random values
    DIRECTLY on the default device — for benches/profilers only. Host
    init of an 8B model costs ~15 min of host RNG on a small VM; timing
    runs don't care about the values. Shapes come from eval_shape over
    the real init+quantize path, so the tree structure is exactly what
    ModelRunner expects."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.model import init_params

    def build(key):
        p = init_params(spec, key)
        if spec.quant == "int8":
            # Traceable twin of quantize_params (which is host/numpy).
            def qw(w, emb=False):
                wf = w.astype(jnp.float32)
                amax = jnp.max(jnp.abs(wf), axis=0 if emb else -2,
                               keepdims=True)
                s = jnp.where(amax == 0, 1.0, amax / 127.0)
                return QTensor(q=jnp.clip(jnp.rint(wf / s), -127, 127)
                               .astype(jnp.int8), s=s)

            layers = dict(p["layers"])
            for k in QUANT_LAYER_KEYS:
                if k in layers:
                    layers[k] = qw(layers[k])
            p = dict(p)
            p["layers"] = layers
            p["embed"] = qw(p["embed"], emb=True)
            if "lm_head" in p:
                p["lm_head"] = qw(p["lm_head"])
        return p

    flat, treedef = jax.tree.flatten(jax.eval_shape(build,
                                                    jax.random.key(0)))

    # numpy RNG per leaf: ~2 orders of magnitude faster than jax's CPU
    # threefry for bulk int8 (the values are irrelevant here), and peak
    # memory stays ~one leaf (a single fused jit program materializing
    # every leaf's RNG intermediate OOMed at 8B).
    import ml_dtypes

    rng = np.random.default_rng(seed)
    leaves = []
    for sds in flat:
        if np.issubdtype(sds.dtype, np.integer):
            leaves.append(rng.integers(-127, 128, size=sds.shape,
                                       dtype=np.int8))
        else:
            # ``scale`` ~0 zeroes every float leaf INCLUDING int8
            # dequant scales -> logits ~0 -> greedy emits one constant
            # token: a stand-in for maximally repetitive text in
            # spec-decode benches (verification still runs the full
            # real-shaped math).
            arr = ((rng.standard_normal(sds.shape, dtype=np.float32)
                    * 0.02 + 0.01) * scale)
            if sds.dtype == jnp.bfloat16:
                arr = arr.astype(ml_dtypes.bfloat16)
            else:
                arr = arr.astype(sds.dtype)
            leaves.append(arr)
    return jax.tree.unflatten(treedef, [jnp.asarray(a) for a in leaves])
