"""KVBM: one auditable policy object for KV block placement across tiers.

The reference manages its KV hierarchy through a dedicated block manager
(lib/llm/src/block_manager.rs G1..G4: device, host, disk, remote) with
explicit offload/onboard policy (block_manager/offload.rs). Before this
module, our tier ladder existed but the POLICY was scattered: the
allocator evicted under allocation pressure only (never proactively),
HostKVCache cascaded to disk as a side effect of put(), promote-on-hit
was implicit in get(), and the G4 peer consult lived inline in
engine._try_onboard. ``KvBlockManager`` centralizes those decisions:

- **Watermark-driven demotion** (``maintain()``): when the HBM free
  list drops below ``low_watermark`` of the pool, LRU inactive blocks
  are demoted to the host tier until ``high_watermark`` is restored —
  hysteresis, so the sweep doesn't thrash around one threshold. An
  allocation burst then finds pages on the free list instead of paying
  evict+extract ordering inside the allocation.
- **Pinned-while-active**: ACTIVE pages are never demotable (the
  allocator's lifecycle invariant), and ``pin()`` additionally protects
  registered-but-inactive blocks (e.g. a fleet-shared system prompt)
  from both the watermark sweep and — by prior onboarding — repeated
  recompute.
- **Promote-on-hit**: a hit in a lower tier moves the block up one
  level (disk→DRAM inside HostKVCache.get; host/peer→HBM via
  ``onboard()``; peer blocks also land in local G2 so the next hit is
  one NIC hop shorter), refreshing LRU recency at each level.
- **Peer tier** (G4): the walk past the local tiers consults
  ``RemoteBlockSource`` (llm/kv_plane.py) — bounded wall-clock budget,
  per-peer breaker discipline — and falls back to recompute, never
  failing the request.

Every demotion sweep, promotion batch, and peer pull emits a typed
journal event (``kv_demote`` / ``kv_promote`` / ``kv_peer_pull``,
runtime/journal.py) with a cause ref, so ``/debug/timeline`` shows tier
churn as part of the fleet's decision history, and ``status()`` is the
single occupancy/counter surface the ``dynamo_tpu_kv_*`` gauges and
``/debug/kv`` read (docs/OBSERVABILITY.md "KV federation").

The manager owns NO device work: uploads/extracts stay in the engine
(the engine thread owns the runner); KVBM decides *what* moves *where*.
"""

from __future__ import annotations

import dataclasses
import time

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kvbm")


@dataclasses.dataclass
class KvbmPolicy:
    """Tier policy knobs (EngineConfig.kvbm_policy(); all per-tier
    budgets live on EngineConfig/HostKVCache — this object holds the
    *decisions* layered on top of those budgets)."""

    # Free-list watermarks as fractions of the HBM pool. The sweep
    # starts when len(free) / num_pages < low and stops at >= high
    # (hysteresis). 0 disables proactive demotion — eviction then only
    # happens under allocation pressure, the pre-KVBM behavior.
    low_watermark: float = 0.0
    high_watermark: float = 0.0
    # At most this many blocks demoted per maintain() call: the sweep
    # runs on the engine thread between windows, and each demotion
    # queues an extract — bound the per-window burst.
    max_demotions_per_sweep: int = 16
    # Journal throttle: tier churn is per-block; one event per sweep /
    # onboard batch, and no more than one per key per this interval.
    journal_min_interval_s: float = 1.0

    def __post_init__(self):
        if self.low_watermark and not self.high_watermark:
            self.high_watermark = min(1.0, self.low_watermark + 0.05)
        if self.high_watermark < self.low_watermark:
            raise ValueError(
                f"kv watermarks inverted: high {self.high_watermark} < "
                f"low {self.low_watermark}")


class KvBlockManager:
    """Placement + eviction policy across HBM → host → disk → peer.

    Wraps the existing mechanism objects (PageAllocator, HostKVCache
    with its DiskKVCache, RemoteBlockSource) without changing their
    storage semantics; the engine delegates its tier decisions here.
    ENGINE THREAD ONLY for maintain()/onboard_walk(); pin/unpin/status
    are safe from any thread (plain reads + set ops under the GIL).
    """

    def __init__(self, allocator, host_cache=None, policy: KvbmPolicy |
                 None = None):
        self.allocator = allocator
        self.host_cache = host_cache
        self.policy = policy or KvbmPolicy()
        # G4 remote tier; assigned by the worker main after the KV plane
        # starts (engine.remote_source property delegates here).
        self.remote_source = None
        # Registered-but-inactive blocks the watermark sweep must not
        # demote (system prompts, shared document prefixes).
        self.pinned: set[int] = set()
        # Policy counters (plain ints, engine thread; exported by
        # engine/kv_metrics.py as deltas).
        self.watermark_demotions = 0
        self.demotion_sweeps = 0
        self.promotions = 0          # blocks moved UP a tier (any rung)
        self.peer_pull_blocks = 0
        self.peer_pull_failures = 0
        self.recompute_fallbacks = 0  # tier walk ended short of the goal
        self.pinned_skips = 0         # sweep passes over pinned blocks
        self._journal_next: dict[str, float] = {}

    # -- pinning --------------------------------------------------------------
    def pin(self, block_hashes) -> None:
        self.pinned.update(block_hashes)

    def unpin(self, block_hashes) -> None:
        self.pinned.difference_update(block_hashes)

    # -- watermark demotion ---------------------------------------------------
    def free_fraction(self) -> float:
        alloc = self.allocator
        return (len(alloc.free) / alloc.num_pages) if alloc.num_pages else 1.0

    def maintain(self) -> int:
        """One engine-loop sweep: demote LRU inactive blocks while the
        free list is under the low watermark, until the high watermark
        (or the sweep budget / the inactive pool) is exhausted. The
        evict hook queues the extracts; the engine's existing
        _flush_spills() dispatches them. Returns blocks demoted."""
        p = self.policy
        if not p.low_watermark or self.host_cache is None:
            return 0
        alloc = self.allocator
        if self.free_fraction() >= p.low_watermark:
            return 0
        target = int(p.high_watermark * alloc.num_pages)
        want = min(p.max_demotions_per_sweep,
                   max(0, target - len(alloc.free)))
        if want <= 0:
            return 0
        before = len(alloc.inactive)
        demoted = alloc.demote_lru(want, skip=self.pinned)
        took = len(demoted)
        # Count pinned passes only when pins actually blocked the sweep
        # (inactive entries remained that demote_lru skipped).
        if took < want and before - took > 0 and self.pinned:
            self.pinned_skips += 1
        if took:
            self.watermark_demotions += took
            self.demotion_sweeps += 1
            if self._journal_due("demote"):
                journal.emit(
                    EventKind.KV_DEMOTE, blocks=took,
                    tier_from="g1", tier_to="g2",
                    free_frac=round(self.free_fraction(), 4),
                    cause=journal.recent_ref(EventKind.KV_DEMOTE,
                                             EventKind.PREEMPT))
        return took

    # -- tier walk (host → disk → peer) ---------------------------------------
    def onboard_walk(self, hashes: list[int], start: int, allowed: int,
                     trace_id: str | None = None):
        """Collect up to ``allowed`` consecutive blocks starting at
        ``hashes[start]`` from the tiers below HBM. Returns
        (blocks [(hash, parcel)], n_peer): host/disk first (HostKVCache
        promotes disk hits to DRAM internally), then one bounded peer
        consult for the remainder. The caller (engine) uploads them into
        HBM pages — that upload IS the promotion to G1, journaled
        here."""
        blocks: list[tuple[int, object]] = []
        if self.host_cache is not None:
            for h in hashes[start:]:
                if len(blocks) >= allowed:
                    break
                kv = self.host_cache.get(h)
                if kv is None:
                    break
                blocks.append((h, kv))
        n_peer = 0
        if self.remote_source is not None and len(blocks) < allowed:
            at = start + len(blocks)
            want = hashes[at:at + (allowed - len(blocks))]
            if want:
                try:
                    remote = self.remote_source.fetch(
                        want, len(want), trace_id=trace_id)
                except Exception:  # noqa: BLE001 — peers are best-effort
                    log.exception("G4 remote fetch failed")
                    self.peer_pull_failures += 1
                    remote = []
                blocks.extend(remote)
                n_peer = len(remote)
                self.peer_pull_blocks += n_peer
        if len(blocks) < allowed:
            # The ladder ran dry before the request's full prefix: the
            # remainder recomputes (always the cheap safe fallback).
            self.recompute_fallbacks += 1
        return blocks, n_peer

    def note_promoted(self, n_host: int, n_peer: int,
                      trace_id: str | None = None) -> None:
        """The engine uploaded ``n_host + n_peer`` tier blocks into HBM
        pages (promote-on-hit completing): account + journal, with the
        peer share attributed to the pull that sourced it."""
        n = n_host + n_peer
        if n <= 0:
            return
        self.promotions += n
        if self._journal_due("promote"):
            journal.emit(
                EventKind.KV_PROMOTE, blocks=n, peer_blocks=n_peer,
                tier_to="g1", trace_id=trace_id,
                cause=journal.recent_ref(EventKind.KV_PEER_PULL,
                                         EventKind.KV_DEMOTE))

    def offload(self, block_hash: int, kv) -> None:
        """Store one extracted block in the host tier (the demotion's
        data movement, called from the engine's spill resolution)."""
        if self.host_cache is not None:
            self.host_cache.put(block_hash, kv)

    # -- observability --------------------------------------------------------
    def _journal_due(self, key: str) -> bool:
        """Per-key journal throttle: tier churn is per-block, the
        timeline wants one event per burst, not thousands."""
        now = time.monotonic()
        if now < self._journal_next.get(key, 0.0):
            return False
        self._journal_next[key] = now + self.policy.journal_min_interval_s
        return True

    def status(self) -> dict:
        """The one auditable surface: policy, pins, counters, and
        per-tier occupancy consistent with the dynamo_tpu_kv_tier_*
        gauges (/debug/kv "kvbm" block)."""
        alloc = self.allocator
        tiers = {"g1": {"blocks": len(alloc.cached),
                        "pages_free": len(alloc.free),
                        "pages_inactive": len(alloc.inactive),
                        "capacity": alloc.num_pages}}
        if self.host_cache is not None:
            hs = self.host_cache.stats()
            tiers["g2"] = {"blocks": hs["g2_blocks"],
                           "capacity": hs["g2_capacity"]}
            if "g3_blocks" in hs:
                tiers["g3"] = {"blocks": hs["g3_blocks"],
                               "capacity": hs["g3_capacity"]}
        if self.remote_source is not None:
            rs = self.remote_source.stats()
            tiers["peer"] = {"peers": rs["peers"],
                             "fetched_blocks": rs["fetched_blocks"]}
        return {
            "policy": {
                "low_watermark": self.policy.low_watermark,
                "high_watermark": self.policy.high_watermark,
                "max_demotions_per_sweep":
                    self.policy.max_demotions_per_sweep,
            },
            "free_fraction": round(self.free_fraction(), 4),
            "pinned_blocks": len(self.pinned),
            "tiers": tiers,
            "watermark_demotions": self.watermark_demotions,
            "demotion_sweeps": self.demotion_sweeps,
            "promotions": self.promotions,
            "peer_pull_blocks": self.peer_pull_blocks,
            "peer_pull_failures": self.peer_pull_failures,
            "recompute_fallbacks": self.recompute_fallbacks,
            "pinned_skips": self.pinned_skips,
        }
