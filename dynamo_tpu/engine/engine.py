"""TPUEngine: continuous batching over the ModelRunner.

The engine thread owns all device work (JAX calls block): it admits waiting
requests (prefill, chunked for long prompts, skipping cached prefix pages),
then runs decode steps over the fixed slot batch, streaming sampled tokens
back to asyncio-land. Replaces vLLM's scheduler+engine in the reference's
worker role (SURVEY.md call stack 3.1 "GPU hot loop"); emits the same KV
events and ForwardPassMetrics the router consumes.
"""

from __future__ import annotations

import asyncio
import dataclasses
import queue
import threading
import time
from typing import AsyncIterator

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.kv_cache import PageAllocator
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, KvStats, WorkerStats
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.llm.tokens import TokenBlockSequence
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("tpu_engine")


@dataclasses.dataclass
class _Request:
    req: PreprocessedRequest
    ctx: Context
    out_q: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    blocks: TokenBlockSequence = None  # type: ignore[assignment]
    pages: list[int] = dataclasses.field(default_factory=list)
    generated: int = 0
    slot: int = -1
    enqueue_t: float = dataclasses.field(default_factory=time.monotonic)

    def push(self, item) -> None:
        self.loop.call_soon_threadsafe(self.out_q.put_nowait, item)


class TPUEngine(AsyncEngine):
    def __init__(self, config: EngineConfig, params=None,
                 devices=None, kv_publisher=None, metrics_publisher=None):
        self.config = config
        self.runner = ModelRunner(config, params=params, devices=devices)
        self.allocator = PageAllocator(self.runner.num_pages, config.page_size)
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        b = config.max_num_seqs
        maxp = config.max_pages_per_seq
        # Slot state (host).
        self.slot_req: list[_Request | None] = [None] * b
        self.tokens = np.zeros(b, np.int32)
        self.positions = np.zeros(b, np.int32)
        self.page_table = np.zeros((b, maxp), np.int32)
        self.seq_lens = np.zeros(b, np.int32)
        self.temperature = np.zeros(b, np.float32)
        self.top_k = np.zeros(b, np.int32)
        self.top_p = np.ones(b, np.float32)
        self.waiting: queue.Queue[_Request] = queue.Queue()
        self.num_waiting = 0
        self._running = False
        self._thread: threading.Thread | None = None
        self._publish_loop: asyncio.AbstractEventLoop | None = None
        self.step_count = 0
        self.prefix_hit_blocks = 0
        self.prefix_lookup_blocks = 0

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        try:
            self._publish_loop = asyncio.get_running_loop()
        except RuntimeError:
            self._publish_loop = None
        self._thread = threading.Thread(target=self._engine_loop,
                                        name="tpu-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None

    # -- AsyncEngine ----------------------------------------------------------
    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        self.start()
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        if not req.token_ids:
            raise ValueError("empty token_ids")
        if len(req.token_ids) >= self.config.max_model_len:
            raise ValueError(
                f"prompt length {len(req.token_ids)} exceeds max model len "
                f"{self.config.max_model_len}")
        r = _Request(req=req, ctx=context, out_q=asyncio.Queue(),
                     loop=asyncio.get_running_loop())
        r.blocks = TokenBlockSequence(self.config.page_size, req.token_ids)
        self.waiting.put(r)
        self.num_waiting += 1
        while True:
            item = await r.out_q.get()
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            yield item
            if item.get("finish_reason"):
                return

    def handler(self):
        async def handle(request, context):
            async for out in self.generate(request, context):
                yield out

        return handle

    # -- engine thread --------------------------------------------------------
    def _engine_loop(self) -> None:
        log.info("engine loop starting (slots=%d pages=%d)",
                 self.config.max_num_seqs, self.runner.num_pages)
        while self._running:
            admitted = self._admit()
            active = [i for i, r in enumerate(self.slot_req) if r is not None]
            if not active:
                if not admitted:
                    time.sleep(0.002)
                continue
            try:
                self._decode_step(active)
            except Exception as exc:  # noqa: BLE001 — fail all, keep serving
                log.exception("decode step failed")
                for i in active:
                    r = self.slot_req[i]
                    if r is not None:
                        r.push(RuntimeError(f"engine step failed: {exc}"))
                        self._free_slot(i, register=False)
            self.step_count += 1
            self._publish()

    def _admit(self) -> bool:
        admitted = False
        while True:
            free_slots = [i for i, r in enumerate(self.slot_req) if r is None]
            if not free_slots:
                return admitted
            try:
                r = self.waiting.get_nowait()
            except queue.Empty:
                return admitted
            self.num_waiting -= 1
            if r.ctx.is_killed or r.ctx.is_stopped:
                r.push(LLMEngineOutput(
                    token_ids=[], finish_reason=FinishReason.CANCELLED).to_wire())
                continue
            try:
                ok = self._prefill_request(r, free_slots[0])
            except Exception as exc:  # noqa: BLE001
                log.exception("prefill failed")
                r.push(RuntimeError(f"prefill failed: {exc}"))
                continue
            if not ok:
                # No KV room: put back and stop admitting.
                self.waiting.put(r)
                self.num_waiting += 1
                return admitted
            admitted = True

    def _prefill_request(self, r: _Request, slot: int) -> bool:
        cfg = self.config
        page = cfg.page_size
        prompt = r.req.token_ids
        hashes = r.blocks.block_hashes
        # Prefix reuse: pin cached pages for the longest cached prefix, but
        # always recompute at least the last token so we have logits.
        cached_pages = self.allocator.acquire_cached(hashes)
        reuse_tokens = len(cached_pages) * page
        if reuse_tokens >= len(prompt):
            drop = (reuse_tokens - len(prompt)) // page + 1
            self.allocator.release(cached_pages[len(cached_pages) - drop:])
            cached_pages = cached_pages[:len(cached_pages) - drop]
            reuse_tokens = len(cached_pages) * page
        self.prefix_lookup_blocks += max(1, len(hashes))
        self.prefix_hit_blocks += len(cached_pages)
        # Pages needed for the rest of the prompt + headroom for generation.
        total_prompt_pages = -(-len(prompt) // page)
        need = total_prompt_pages - len(cached_pages)
        new_pages = self.allocator.allocate(need)
        if new_pages is None:
            self.allocator.release(cached_pages)
            return False
        pages = cached_pages + new_pages
        r.pages = pages
        # Chunked prefill over buckets.
        start = reuse_tokens
        max_chunk = min(cfg.max_prefill_tokens, cfg.prefill_buckets[-1])
        first_token = None
        while start < len(prompt):
            n = min(max_chunk, len(prompt) - start)
            # Chunks must start at page boundaries (start is one by
            # construction); align chunk length to page size unless final.
            chunk_tokens = np.asarray(prompt[start:start + n], np.int32)
            first_page = start // page
            chunk_pages = np.asarray(
                pages[first_page:first_page + (-(-n // page))], np.int32)
            hist = np.asarray(pages[:first_page], np.int32)
            sampling = self._sampling_of(r)
            token, _ = self.runner.prefill(
                chunk_tokens, start, chunk_pages,
                hist if len(hist) else None, sampling)
            start += n
            if start >= len(prompt):
                first_token = token
        assert first_token is not None
        self._place_in_slot(r, slot, first_token)
        return True

    def _sampling_of(self, r: _Request) -> tuple[float, int, float]:
        s = r.req.sampling_options
        return (s.temperature or 0.0, s.top_k or 0, s.top_p or 1.0)

    def _place_in_slot(self, r: _Request, slot: int, first_token: int) -> None:
        prompt_len = len(r.req.token_ids)
        # The prompt's complete blocks are now resident: register them for
        # prefix reuse + router events.
        for idx, h in enumerate(r.blocks.block_hashes):
            self.allocator.register(r.pages[idx], h)
        r.generated = 1  # the prefill sampled the first token
        finish = self._check_finish(r, first_token)
        self._emit_token(r, first_token, finish)
        if finish is not None:
            self.allocator.release(r.pages)
            r.pages = []
            return
        r.slot = slot
        self.slot_req[slot] = r
        self.tokens[slot] = first_token
        self.positions[slot] = prompt_len  # where the new token will be written
        self.page_table[slot, :len(r.pages)] = r.pages
        self.seq_lens[slot] = prompt_len + 1
        temp, tk, tp = self._sampling_of(r)
        self.temperature[slot] = temp
        self.top_k[slot] = tk
        self.top_p[slot] = tp

    def _decode_step(self, active: list[int]) -> None:
        cfg = self.config
        page = cfg.page_size
        # Ensure every active slot has a page for the position being written.
        for i in active:
            r = self.slot_req[i]
            needed_pages = self.positions[i] // page + 1
            if needed_pages > self.config.max_pages_per_seq:
                r.push(LLMEngineOutput(
                    token_ids=[], finish_reason=FinishReason.LENGTH).to_wire())
                self._free_slot(i, register=True)
                continue
            while len(r.pages) < needed_pages:
                new = self.allocator.allocate(1)
                if new is None:
                    # Out of KV: fail this request (preemption lands with the
                    # KVBM offload tier).
                    r.push(RuntimeError("KV pool exhausted"))
                    self._free_slot(i, register=False)
                    break
                r.pages.extend(new)
                self.page_table[i, len(r.pages) - 1] = new[0]
            if self.slot_req[i] is None:
                active = [j for j in active if j != i]
        if not active:
            return
        sampled = self.runner.decode(
            self.tokens, self.positions, self.page_table, self.seq_lens,
            self.temperature, self.top_k, self.top_p)
        for i in active:
            r = self.slot_req[i]
            if r is None:
                continue
            token = int(sampled[i])
            if r.ctx.is_killed:
                r.push(None)
                self._free_slot(i, register=True)
                continue
            if r.ctx.is_stopped:
                r.push(LLMEngineOutput(
                    token_ids=[], finish_reason=FinishReason.CANCELLED).to_wire())
                self._free_slot(i, register=True)
                continue
            r.generated += 1
            new_block = r.blocks.append(self.tokens[i])
            if new_block is not None:
                # Register the just-completed page under its chained hash.
                page_idx = (len(r.blocks.tokens) // page) - 1
                self.allocator.register(r.pages[page_idx], new_block)
            finish = self._check_finish(r, token)
            self._emit_token(r, token, finish)
            if finish is not None:
                self._free_slot(i, register=True)
            else:
                self.tokens[i] = token
                self.positions[i] += 1
                self.seq_lens[i] += 1

    def _check_finish(self, r: _Request, token: int) -> FinishReason | None:
        sc = r.req.stop_conditions
        if r.generated >= (sc.max_tokens or 2**30):
            return FinishReason.LENGTH
        if sc.min_tokens and r.generated < sc.min_tokens:
            return None
        if not sc.ignore_eos and token in (r.req.eos_token_ids or []):
            return FinishReason.EOS
        if token in (sc.stop_token_ids or []):
            return FinishReason.STOP
        return None

    def _emit_token(self, r: _Request, token: int,
                    finish: FinishReason | None = None) -> None:
        r.push(LLMEngineOutput(token_ids=[token],
                               finish_reason=finish).to_wire())

    def _free_slot(self, slot: int, register: bool) -> None:
        r = self.slot_req[slot]
        self.slot_req[slot] = None
        # Reset the slot's device-facing state to the reserved scratch page 0:
        # decode_forward scatters K/V for EVERY slot each step, so a freed
        # slot's dummy writes must land on the scratch page, never on pages
        # that have been released and reallocated to live requests.
        self.tokens[slot] = 0
        self.positions[slot] = 0
        self.seq_lens[slot] = 0
        self.page_table[slot, :] = 0
        if r is None:
            return
        if not register:
            # Failure path: the pages' KV contents are suspect (partial
            # prefill / failed step) — drop their prefix-cache entries so no
            # future request reuses them.
            self.allocator.unregister(r.pages)
        self.allocator.release(r.pages)
        r.pages = []

    # -- metrics + events -----------------------------------------------------
    def _publish(self) -> None:
        loop = self._publish_loop
        if loop is None or loop.is_closed():
            self.allocator.drain_events()
            return
        stored, removed = self.allocator.drain_events()
        active = sum(1 for r in self.slot_req if r is not None)
        hit = (self.prefix_hit_blocks / self.prefix_lookup_blocks
               if self.prefix_lookup_blocks else 0.0)
        metrics = ForwardPassMetrics(
            worker_stats=WorkerStats(
                request_active_slots=active,
                request_total_slots=self.config.max_num_seqs,
                num_requests_waiting=self.num_waiting),
            kv_stats=KvStats(
                kv_active_blocks=self.allocator.num_active,
                kv_total_blocks=self.allocator.num_pages,
                gpu_cache_usage_perc=(self.allocator.num_active
                                      / self.allocator.num_pages),
                gpu_prefix_cache_hit_rate=hit))

        async def do_publish():
            try:
                if self.kv_publisher is not None:
                    if stored:
                        await self.kv_publisher.stored(stored)
                    if removed:
                        await self.kv_publisher.removed(removed)
                if self.metrics_publisher is not None:
                    force = active == 0 and self.num_waiting == 0
                    await self.metrics_publisher.publish(metrics, force=force)
            except Exception:  # noqa: BLE001
                log.exception("publish failed")

        if (self.kv_publisher is not None or self.metrics_publisher is not None):
            asyncio.run_coroutine_threadsafe(do_publish(), loop)
