"""TPUEngine: continuous batching over the ModelRunner.

The engine thread owns all device work (JAX calls block): it admits waiting
requests (batched prefill; chunked for long prompts; cached prefix pages are
skipped), then decodes in M-step WINDOWS: one device program runs M decode
steps with tokens chained on-device, so the per-token path has no
host<->device round-trip. While window w executes, the host processes window
w-1's tokens (async readback), emits them to streams, registers completed
blocks, and prepares page tables — a software pipeline replacing the
reference's per-step GPU loop (SURVEY.md call stack 3.1 "GPU hot loop");
emits the same KV events and ForwardPassMetrics the router consumes.

KV-pressure policy: when the pool is exhausted mid-decode the engine
preempts the youngest slot — its pages are released (prefix-cache entries
kept) and the request is requeued to re-prefill from its accumulated tokens
(reference vLLM preempt-and-recompute semantics) — instead of failing it.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import dataclasses
import os
import queue
import threading
import time
from typing import AsyncIterator

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.kv_cache import PageAllocator
from dynamo_tpu.engine.runner import (
    ModelRunner, PrefillSeq, PK_OVERRIDE, PK_TOKEN, PK_POS, PK_SEQLEN,
    PK_TOPK, PK_TEMP, PK_TOPP, PK_CAP, PK_LOGPROB, PK_FREQPEN, PK_PRESPEN,
    PK_SEED, PK_SEEDED, PK_ADAPTER, PK_PREFIX, TOP_LOGPROBS)
from dynamo_tpu.engine.sampler import MAX_TOPK
from dynamo_tpu.llm.kv_router.protocols import (ForwardPassMetrics, KvStats,
                                                SpecDecodeStats, WorkerStats)
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.llm.tokens import TokenBlockSequence
from dynamo_tpu.engine import perf as perf_plane
from dynamo_tpu.runtime import chaos, flight, journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import current_trace, get_logger
from dynamo_tpu.runtime.tracing import (_LATENCY_BUCKETS, get_recorder,
                                        phase_metrics)

log = get_logger("tpu_engine")


@dataclasses.dataclass
class _Request:
    req: PreprocessedRequest
    ctx: Context
    out_q: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    tokens_all: list[int] = dataclasses.field(default_factory=list)
    blocks: TokenBlockSequence = None  # type: ignore[assignment]
    pages: list[int] = dataclasses.field(default_factory=list)
    generated: int = 0
    slot: int = -1
    epoch: int = 0
    # None = first token still on device (async fetch pending).
    last_token: int | None = -1
    reuse_tokens: int = 0  # cached-prefix tokens pinned by the last plan
    # Disaggregation: (first_token, kv [2,L,Nkv,n,page,D]) from a remote
    # prefill — admission inserts the pages instead of prefilling locally.
    injected: tuple | None = None
    enqueue_t: float = dataclasses.field(default_factory=time.monotonic)
    # Upper bound on total sequence length (original prompt + max_tokens):
    # dispatch never allocates pages past it, so pipelined lookahead can't
    # demand pages a finishing request will never write.
    len_cap: int = 2**30
    # Multimodal requests skip the prefix cache entirely: the placeholder
    # ids under media spans would alias unrelated media in the
    # content-hash space. mm_buf carries the parsed full-prompt
    # (embeddings, mask) for the chunked path.
    no_cache: bool = False
    mm_buf: tuple | None = None
    # SLA-admission ledger entries: cold tokens this request contributes
    # while queued (full prompt; reuse unknown until planned) and while
    # admitted-but-first-token-unresolved (prompt minus prefix reuse).
    queued_cold: int = 0
    cold_tokens: int = 0
    # Queue-wait observed for the current stint (reset on requeue so a
    # preempted request's second wait records too).
    wait_noted: bool = False
    # Stall-free chunked prefill: while True the request owns a slot and
    # pages but is still being prefilled by SCHEDULED chunk dispatches
    # (decode windows never touch the slot). prefill_pos is the next
    # prompt position to dispatch; prefill_t0 anchors the end-to-end
    # prefill phase (admission -> first-token readback).
    prefilling: bool = False
    prefill_pos: int = 0
    prefill_t0: float = 0.0
    # Batched LoRA (engine/lora.py): the resident device slot this
    # request's adapter occupies (0 = base model) and the store
    # reference held while the request is live (released at slot
    # finish; a requeued request re-acquires at re-admission).
    adapter_slot: int = 0
    adapter_ref: str | None = None

    def push(self, item) -> None:
        self.loop.call_soon_threadsafe(self.out_q.put_nowait, item)


@dataclasses.dataclass
class _Window:
    toks: object  # [M,B] device array (or None when no active rows)
    slots: list   # per slot: (request, epoch, start_pos, cap) or None
    frozen: dict  # slot -> (request, epoch, "requeue" | "oom")
    size: int
    serial: int = 0  # dispatch order (pipelined deferred-release fencing)
    t0: float = 0.0  # dispatch time (decode_step_seconds + decode spans)
    # Speculative windows: toks = (outs [m,B,S], emits [m,B],
    # ndrafts [m,B]); slots snaps carry the ASSUMED advance so
    # processing can correct the host's upper-bound positions.
    spec: bool = False


class TPUEngine(AsyncEngine):
    def __init__(self, config: EngineConfig, params=None,
                 devices=None, kv_publisher=None, metrics_publisher=None,
                 metrics_registry=None):
        self.config = config
        # Tracing + phase histograms (runtime/tracing.py). The recorder
        # is the process-global ring buffer; the histograms need a
        # MetricsRegistry node and stay None without one (call sites
        # without a runtime lose metrics, never correctness).
        self._recorder = get_recorder()
        self.phase = (phase_metrics(metrics_registry)
                      if metrics_registry is not None else None)
        self.decode_window = config.resolve_decode_window()
        self.prefill_chunk_tokens = config.resolve_prefill_chunk_tokens()
        self.runner = ModelRunner(config, params=params, devices=devices)
        self.allocator = PageAllocator(self.runner.num_pages, config.page_size)
        # KV tiering (G2 host DRAM + optional G3 disk): HBM evictions are
        # offloaded via async extracts; prefix hits on spilled blocks are
        # onboarded by upload instead of recomputing the prefill.
        self.host_cache = None
        if config.host_cache_pages > 0 or config.kv_disk_cache_dir:
            from dynamo_tpu.engine.kv_host_cache import (DiskKVCache,
                                                         HostKVCache)
            disk = (DiskKVCache(config.kv_disk_cache_dir,
                                config.disk_cache_pages)
                    if config.kv_disk_cache_dir else None)
            # A disk tier with no G2 capacity still needs a small DRAM
            # front (demotions flow through it).
            capacity = config.host_cache_pages or 16
            self.host_cache = HostKVCache(capacity, disk)
            self.allocator.evict_hook = self._on_evict
        # KVBM (engine/kvbm.py): the placement/eviction POLICY across
        # HBM -> host -> disk -> peer as one auditable object — watermark
        # demotion, pinning, promote-on-hit accounting, the G4 peer walk.
        # The engine keeps the device work (extracts/uploads); the
        # manager decides what moves where and journals it.
        from dynamo_tpu.engine.kvbm import KvBlockManager
        self.kvbm = KvBlockManager(self.allocator, self.host_cache,
                                   config.kvbm_policy())
        self._evict_buffer: list[tuple[int, int]] = []
        self._pending_spills: list[dict] = []
        self.onboard_blocks = 0
        self.g4_blocks = 0
        self.streamed_extracts = 0  # chunk-streamed disagg tickets staged
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        # Set by the worker main when the KV data plane runs: the plane
        # server (outbound stats) and the periodic inventory-digest
        # publisher (docs/OBSERVABILITY.md "KV & capacity").
        self.plane = None
        self.inventory_publisher = None
        # dynamo_tpu_kv_* exporter (engine/kv_metrics.py): allocator /
        # tier / plane telemetry onto /metrics, throttled internally.
        self.kv_metrics = None
        if metrics_registry is not None:
            from dynamo_tpu.engine.kv_metrics import KvMetricsUpdater
            self.kv_metrics = KvMetricsUpdater(metrics_registry)
        # Multi-tenant batched LoRA (engine/lora.py; config.max_adapters
        # > 0): the store owns adapter registration, device-slot LRU
        # placement and hot-loads — the engine resolves a request's
        # adapter name at admission (engine thread: the upload is device
        # work) and threads the slot id through every dispatch.
        self.adapters = None
        if config.max_adapters > 0:
            from dynamo_tpu.engine.lora import AdapterStore
            self.adapters = AdapterStore(self.runner, config.max_adapters,
                                         config.lora_max_rank)
        self.adapter_metrics = None
        if metrics_registry is not None and self.adapters is not None:
            from dynamo_tpu.engine.kv_metrics import AdapterMetricsUpdater
            self.adapter_metrics = AdapterMetricsUpdater(metrics_registry)
        b = config.max_num_seqs
        # Slot state (host view; tokens chain on-device between windows).
        self.slot_req: list[_Request | None] = [None] * b
        # Per-slot resident adapter ids for the decode-window control
        # array (0 = base model).
        self.adapter_ids = np.zeros(b, np.int32)
        self.disp_positions = np.zeros(b, np.int64)
        self.disp_seq_lens = np.zeros(b, np.int64)
        self.temperature = np.zeros(b, np.float32)
        self.top_k = np.zeros(b, np.int32)
        self.top_p = np.ones(b, np.float32)
        self.freq_pen = np.zeros(b, np.float32)
        self.pres_pen = np.zeros(b, np.float32)
        self.seeds = np.zeros(b, np.int32)
        self.seeded = np.zeros(b, bool)
        self.overrides: dict[int, int] = {}  # slot -> first token next window
        self.waiting: queue.Queue[_Request] = queue.Queue()
        self.num_waiting = 0
        # Queue-accounting counters are read-modify-written from BOTH the
        # event loop (generate -> _queue_put) and the engine thread
        # (_admit / requeue): unguarded `+=` loses updates, and these
        # counters feed the SLA admission gate and TTFT projection
        # (caught by dtpu-lint engine-thread-shared-state).
        self._queue_stats_lock = threading.Lock()
        # SLA-aware admission (config.ttft_budget_ms): the measured
        # end-to-end prefill rate (EWMA over batched-prefill dispatch ->
        # first-token-readback intervals, so queueing behind decode
        # windows is priced in) and the cold-token ledger the TTFT
        # projection runs on. The disagg prefill-extract job path
        # (run_job) bypasses this — its admission belongs to the queue
        # dispatcher's depth backpressure (llm/prefill_queue.py).
        self.prefill_rate_tok_s: float | None = None
        self._cold_inflight = 0   # admitted; first token not yet resolved
        self._waiting_cold = 0    # queued; not yet admitted
        self.admission_deferred = 0  # gate held the queue head back
        # Deferred queue HEAD: the SLA gate parks the over-budget head
        # here instead of re-queueing at the tail — strict FIFO, so a
        # large prompt can't be starved by a stream of later small ones
        # slipping under the budget.
        self._deferred_head: _Request | None = None
        # Speculative decoding (config.spec_decode="ngram"): outer verify
        # steps per window sized so the worst case (nothing accepted
        # costs m_outer weight reads, everything accepted yields the
        # full M tokens for m_outer reads). Stats feed SpecDecodeStats.
        self.spec_m_outer = (max(1, self.decode_window
                                 // (config.spec_k + 1))
                             if config.spec_decode else 0)
        self.spec_drafts = 0        # verify steps that had drafts
        self.spec_tokens = 0        # draft tokens proposed
        self.spec_accepted = 0      # draft tokens accepted
        # Per-verify-step emitted-token histogram: index e = tokens the
        # step emitted (1 = no draft accepted .. spec_k+1 = all
        # accepted); index 0 counts dispatched-but-frozen steps.
        self.spec_emit_hist = ([0] * (config.spec_k + 2)
                               if config.spec_decode else [])
        # Engine-local brownout (see _update_brownout): 0..3 pressure
        # level from the TTFT projection; spec_brownout_windows counts
        # decode windows where drafting was suspended by it.
        self.brownout_level = 0
        self.spec_brownout_windows = 0
        # Control jobs executed on the engine thread between windows
        # (disagg prefill-extract, KV injection helpers, etc.).
        self._jobs: queue.Queue = queue.Queue()
        # Dispatched-but-unprocessed windows, oldest first. Depth > 1
        # overlaps the host<->device round trips of consecutive windows.
        self._inflight: collections.deque[_Window] = collections.deque()
        self._dispatch_serial = 0
        # Batched-prefill first tokens awaiting async device->host fetch:
        # {"handle": device array, "rows": [(row, request, slot, epoch)]}.
        self._pending_first: list[dict] = []
        # Pages freed while windows that may still scatter to them are in
        # flight: (serial of the newest dispatched window at free time,
        # pages). Released once that window has been processed.
        self._pending_release: list[tuple[int, list[int]]] = []
        # Stall-free chunked prefill: requests whose long prompts are
        # scheduled as interleaved chunk work (oldest-first fair share of
        # prefill_chunk_tokens per loop iteration), and the chunk
        # programs dispatched but not yet observed complete (bounded by
        # pipeline_depth like decode windows).
        self._prefilling: list[_Request] = []
        self._chunk_inflight: collections.deque[dict] = collections.deque()
        self.chunk_tokens_total = 0     # prompt tokens dispatched as chunks
        self.chunk_dispatch_count = 0   # chunk programs dispatched
        self.decode_stall_max_s = 0.0   # widest observed dispatch gap
        self._last_decode_dispatch: float | None = None
        self.m_chunk_tokens = self.m_chunks_inflight = None
        self.m_decode_stall = None
        if metrics_registry is not None:
            self.m_chunk_tokens = metrics_registry.counter(
                "prefill_chunk_tokens_total",
                "Prompt tokens dispatched as scheduled prefill chunks")
            self.m_chunks_inflight = metrics_registry.gauge(
                "prefill_chunks_inflight",
                "Prefill chunk programs dispatched but not yet retired")
            self.m_decode_stall = metrics_registry.histogram(
                "decode_stall_seconds",
                "Gap between consecutive decode-window dispatches while "
                "decode slots are active",
                buckets=_LATENCY_BUCKETS)
            for bound in (self.m_chunk_tokens, self.m_chunks_inflight,
                          self.m_decode_stall):
                bound.ensure()
        # Flight recorder (runtime/flight.py): one compact row per
        # processed decode window into the process-global ring; the
        # deltas below turn cumulative counters into per-window values.
        self._flight = flight.get_recorder()
        self._flight_chunk_last = 0
        self._flight_stall_last = 0.0
        self._flight_tokens_last = 0
        # Perf plane (engine/perf.py): per-window roofline attribution
        # feeds the process-global compile registry; the exporter turns
        # it into dynamo_tpu_perf_* series alongside HBM gauges.
        self._perf = perf_plane.get_registry()
        self._perf_tokens_last = 0
        self.tokens_generated_total = 0  # decode-window tokens emitted
        self._step_floor_ms = config.model.weight_read_step_ms(
            config.tp, config.pp)
        self.perf_metrics = None
        if metrics_registry is not None:
            self.perf_metrics = perf_plane.PerfMetricsUpdater(
                metrics_registry)
        self._running = False
        self._thread: threading.Thread | None = None
        self._publish_loop: asyncio.AbstractEventLoop | None = None
        self.step_count = 0
        self.prefix_hit_blocks = 0
        self.prefix_lookup_blocks = 0
        self.preempt_count = 0
        # Recent victims (bounded; observability + tests).
        self.preempted_ids: collections.deque[str] = collections.deque(
            maxlen=64)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        try:
            self._publish_loop = asyncio.get_running_loop()
        except RuntimeError:
            self._publish_loop = None
        self._thread = threading.Thread(target=self._engine_loop,
                                        name="tpu-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None

    # -- AsyncEngine ----------------------------------------------------------
    def _validate(self, req: PreprocessedRequest) -> None:
        if not req.token_ids:
            raise ValueError("empty token_ids")
        if self.config.spec_decode:
            # Spec decode serves the full sampling surface on-device
            # (temperature/top-k/top-p/seed as data in the verify
            # program; every emitted token is exactly target-distributed
            # via rejection sampling). Still outside it: logprobs (the
            # verify program has no per-step logprob taps) and OpenAI
            # penalties (the [B,V] count state doesn't thread through
            # the spec scan).
            s = req.sampling_options
            unsupported = []
            if s.logprobs is not None:
                unsupported.append("logprobs")
            if getattr(s, "frequency_penalty", None) or \
                    getattr(s, "presence_penalty", None):
                unsupported.append("frequency/presence penalties")
            if unsupported:
                raise ValueError(
                    f"speculative decoding ({self.config.spec_decode}) "
                    f"does not support: {', '.join(unsupported)}. "
                    f"Disable spec_decode or drop these options "
                    f"(temperature/top_k/top_p/seed are supported)")
        if len(req.token_ids) >= self.config.max_model_len:
            raise ValueError(
                f"prompt length {len(req.token_ids)} exceeds max model len "
                f"{self.config.max_model_len}")
        adapter = getattr(req, "adapter", None)
        if adapter:
            from dynamo_tpu.runtime.errors import AdapterNotFoundError
            if self.adapters is None:
                raise AdapterNotFoundError(
                    f"adapter {adapter!r} requested but this engine "
                    f"serves no adapters (--max-adapters 0)")
            if not self.adapters.registered(adapter):
                # Fail fast at generate() — the authoritative (slot)
                # resolution happens at admission on the engine thread.
                raise AdapterNotFoundError(
                    f"adapter {adapter!r} is not registered on this "
                    f"worker (serving: {self.adapters.names() or 'none'})")
        s = req.sampling_options
        if s.logprobs is not None and s.logprobs > TOP_LOGPROBS:
            log.warning("top_logprobs=%d exceeds cap %d; clamping",
                        s.logprobs, TOP_LOGPROBS)
            s.logprobs = TOP_LOGPROBS
        if s.top_k and s.top_k > MAX_TOPK:
            # The sampler prefilters to the top-MAX_TOPK candidates (no
            # full-vocab sort on TPU) — top-k beyond that, and the top-p
            # nucleus, operate within those candidates. Clamp visibly
            # rather than silently truncating inside the kernel.
            log.warning(
                "top_k=%d exceeds sampler cap %d; clamping (top-k/top-p "
                "sample among the top-%d logits)", s.top_k, MAX_TOPK,
                MAX_TOPK)
            s.top_k = MAX_TOPK
        if getattr(s, "seed", None) is not None and \
                not 0 <= s.seed <= 0x7FFFFFFF:
            from dynamo_tpu.engine.runner import mask_seed
            log.warning("seed=%s outside the engine's 31-bit seed space; "
                        "using %d (distinct large seeds can collide)",
                        s.seed, mask_seed(s.seed))
        for field in ("frequency_penalty", "presence_penalty"):
            val = getattr(s, field, None)
            if val is not None and not -2.0 <= val <= 2.0:
                clamped = max(-2.0, min(2.0, val))
                log.warning("%s=%s outside [-2, 2]; clamping to %s",
                            field, val, clamped)
                setattr(s, field, clamped)


    # -- SLA-aware admission ---------------------------------------------------
    def _queue_put(self, r: _Request, cold: int | None = None) -> None:
        """Enqueue for admission, tracking the queued cold tokens the
        TTFT projection counts (every put site must come through here)."""
        r.queued_cold = len(r.tokens_all) if cold is None else cold
        with self._queue_stats_lock:
            self._waiting_cold += r.queued_cold
            self.num_waiting += 1
        self.waiting.put(r)

    def _queue_pop_accounting(self, r: _Request) -> None:
        with self._queue_stats_lock:
            self._waiting_cold -= r.queued_cold
            self.num_waiting -= 1
        r.queued_cold = 0

    def _note_queue_wait(self, r: _Request) -> None:
        """Admission reached: observe how long the request sat in the
        waiting queue (requeued requests keep their original enqueue
        time, so this is total time-to-slot, the operator-facing
        number). ENGINE THREAD."""
        if r.wait_noted:
            return
        r.wait_noted = True
        now = time.monotonic()
        if self.phase is not None:
            self.phase.queue_wait.observe(now - r.enqueue_t)
        rec = self._recorder
        if rec.enabled:
            rec.add("engine.queue_wait", r.ctx.trace_id, r.ctx.span_id,
                    r.enqueue_t, now)

    def _maybe_reject(self, prompt_tokens: int) -> None:
        """Raise OverloadedError (frontend: HTTP 503, router retries
        elsewhere) when the projected TTFT through the current backlog
        exceeds budget x reject_factor. Never rejects an idle engine:
        with no backlog the request's TTFT is its own prefill, which the
        budget can't improve by bouncing it."""
        cfg = self.config
        if not (cfg.ttft_budget_ms and cfg.admission_reject_factor):
            return
        backlog = self._cold_inflight + self._waiting_cold
        rate = self.prefill_rate_tok_s
        if backlog <= 0 or not rate:
            return
        projected = (backlog + prompt_tokens) / rate * 1e3
        limit = cfg.ttft_budget_ms * cfg.admission_reject_factor
        if projected > limit:
            from dynamo_tpu.runtime.errors import OverloadedError
            raise OverloadedError(
                f"projected TTFT {projected:.0f} ms exceeds "
                f"{limit:.0f} ms ({backlog} cold tokens backlogged at "
                f"{rate:.0f} tok/s)")

    def _prefill_rate_sample(self, tokens: int, elapsed_s: float) -> None:
        if tokens <= 0 or elapsed_s <= 1e-6:
            return
        s = tokens / elapsed_s
        self.prefill_rate_tok_s = (
            s if self.prefill_rate_tok_s is None
            else 0.7 * self.prefill_rate_tok_s + 0.3 * s)

    def estimated_ttft_ms(self, extra_tokens: int = 0) -> float | None:
        """Projected TTFT for a hypothetical arrival, from the measured
        prefill rate and the cold-token backlog. None until the first
        prefill has calibrated the rate.

        Chunked-prefill backlog is included: a long prompt's cold tokens
        stay in the ledger from admission until its FINAL chunk's
        first-token readback, and the rate EWMA is sampled over that same
        end-to-end interval — so the interleaved decode windows the
        chunk scheduler inserts are priced into the projection, and the
        frontend's deadline shedding / brownout (runtime/overload.py)
        sees long prompts at their true cost."""
        if not self.prefill_rate_tok_s:
            return None
        return ((self._cold_inflight + self._waiting_cold + extra_tokens)
                / self.prefill_rate_tok_s * 1e3)

    async def generate(self, request, context: Context) -> AsyncIterator[dict]:
        self.start()
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        self._validate(req)
        # One emitted item per generated token, capped by len_cap; the
        # consumer is this generator's own caller.
        # dtpu: ignore[unbounded-queue] -- bounded by max_tokens via len_cap
        r = _Request(req=req, ctx=context, out_q=asyncio.Queue(),
                     loop=asyncio.get_running_loop(),
                     tokens_all=list(req.token_ids),
                     len_cap=len(req.token_ids)
                     + (req.stop_conditions.max_tokens or 2**30))
        self._maybe_reject(len(req.token_ids))
        # Request loop logs (admission warnings, preemptions surfaced to
        # the caller) carry the request's trace context.
        trace_tok = current_trace.set(
            {"trace_id": context.trace_id, "span_id": context.span_id})
        self._queue_put(r)
        try:
            while True:
                item = await r.out_q.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.get("finish_reason"):
                    return
        finally:
            try:
                current_trace.reset(trace_tok)
            except ValueError:  # generator finalized from another context
                pass

    async def generate_injected(self, request, context: Context,
                                first_token: int, kv) -> AsyncIterator[dict]:
        """Serve a request whose prompt KV was prefilled REMOTELY: admission
        inserts the transferred pages and decoding starts at first_token
        (disaggregated decode side; reference handlers.py:113-162)."""
        self.start()
        req = (request if isinstance(request, PreprocessedRequest)
               else PreprocessedRequest.from_wire(request))
        self._validate(req)
        # dtpu: ignore[unbounded-queue] -- bounded by max_tokens via len_cap
        r = _Request(req=req, ctx=context, out_q=asyncio.Queue(),
                     loop=asyncio.get_running_loop(),
                     tokens_all=list(req.token_ids),
                     injected=(first_token, kv),
                     len_cap=len(req.token_ids)
                     + (req.stop_conditions.max_tokens or 2**30),
                     # The injected path never runs _plan_prefill, so the
                     # multimodal no-cache flag must be set here: the
                     # placeholder-id hash chain must not enter the
                     # prefix cache pointing at media-conditioned KV.
                     no_cache=bool(getattr(req, "mm_embeds", None)))
        # Injected requests carry their KV with them — no cold prefill,
        # so the SLA gate and the cold ledger both skip them.
        trace_tok = current_trace.set(
            {"trace_id": context.trace_id, "span_id": context.span_id})
        self._queue_put(r, cold=0)
        try:
            while True:
                item = await r.out_q.get()
                if item is None:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.get("finish_reason"):
                    return
        finally:
            try:
                current_trace.reset(trace_tok)
            except ValueError:
                pass

    # -- engine-thread jobs (disaggregation control path) ---------------------
    async def run_job(self, fn):
        """Run ``fn`` on the engine thread (which owns all device work)
        between windows; await its result."""
        self.start()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._jobs.put((fn, fut))
        return await asyncio.wrap_future(fut)

    def _run_jobs(self) -> None:
        while True:
            try:
                fn, fut = self._jobs.get_nowait()
            except queue.Empty:
                return
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except Exception as exc:  # noqa: BLE001 — deliver to caller
                fut.set_exception(exc)

    def prefill_extract(self, req: PreprocessedRequest):
        """ENGINE-THREAD ONLY (call via run_job). Prefill a prompt, register
        its blocks in the prefix cache, and extract the prompt's KV pages to
        host. Returns (first_token, kv [2,L,Nkv,n,page,D], prompt_len) —
        the disaggregated prefill side (reference PrefillWorkerHandler,
        handlers.py:167-199)."""
        first_token, handle, prompt_len = self._prefill_for_extract(req)
        return first_token, self.runner.finalize_extract(handle), prompt_len

    def _prefill_for_extract(self, req: PreprocessedRequest,
                             grouped: bool = False):
        """Prefill + dispatch the page gather; returns the UNRESOLVED
        extract handle so the device->host copy can overlap whatever the
        caller does next (stage-for-pull, decode windows). With
        ``grouped``, dispatches up to 4 page-group gathers instead of one
        (their D2H copies all start now; the plane then streams group i
        while group i+1's copy completes) and returns a list of
        handles."""
        self._reject_adapter_extract(req)
        self._validate(req)
        r = _Request(req=req, ctx=Context(), out_q=None, loop=None,  # type: ignore[arg-type]
                     tokens_all=list(req.token_ids))
        plan = self._plan_prefill(r)
        if plan is None:
            raise RuntimeError("prefill worker KV pool exhausted")
        try:
            if plan == "chunked":
                first_token = self._prefill_chunked_token(r)
            else:
                first_token = int(self.runner.prefill_batch([plan])[0])
            if not r.no_cache:
                for idx, h in enumerate(r.blocks.block_hashes):
                    self.allocator.register(r.pages[idx], h)
            if grouped:
                n = len(r.pages)
                per = -(-n // min(4, max(1, n)))
                handle = [self.runner.extract_pages_async(r.pages[i:i + per])
                          for i in range(0, n, per)]
            else:
                handle = self.runner.extract_pages_async(r.pages)
        finally:
            # The gather is dispatched: device-stream order guarantees it
            # reads the pages before any later program can overwrite them,
            # so the pages release immediately.
            self.allocator.release(r.pages)
            r.pages = []
        return first_token, handle, len(r.tokens_all)

    def prefill_extract_staged(self, req: PreprocessedRequest, plane,
                               on_ticket=None):
        """ENGINE-THREAD ONLY (call via run_job). Disaggregated prefill
        over the direct KV data plane: prefill, stage the extract with
        the plane (host fetches resolve lazily on the plane thread,
        overlapping this engine's next windows), return (first_token,
        ticket, prompt_len). The ticket rides the small response stream;
        the KV bytes take the plane's direct path (llm/kv_plane.py) —
        the jax device path when the parcel shape allows it, else the
        socket path with PIPELINED page groups (extract was ~97% of the
        round-4 transfer tax; reference offload.rs overlap role).

        ``on_ticket`` (threadsafe callable) enables CHUNK-STREAMED
        extract: the ticket is staged and delivered BEFORE prefill
        completes, with one page group per prefill chunk gated on that
        chunk's extract — the decode worker pulls KV while later chunks
        are still computing, hiding the per-prompt transfer tax
        (PERF_NOTES' 15-20 ms projection) behind prefill compute."""
        spec = self.runner.spec
        page = self.config.page_size
        n = -(-len(req.token_ids) // page)
        quant = self.runner.quant_kv == "int8"
        # The jax device-path needs the staged array to be EXACTLY the
        # advertised shape; the gather output is bucket-padded and
        # kv-head-replicated, so only offer it when neither applies —
        # and quantized parcels are host-packed (int8+scales -> uint8),
        # so they always take the socket path.
        dev_ok = (getattr(plane, "_use_jax", False)
                  and self.runner.kv_rep == 1
                  and self.runner._page_bucket(n) == n
                  and not quant)
        # Socket-path grouping only helps when per-fetch D2H latency is
        # small (local attachment); a tunneled chip pays its ~100 ms RTT
        # floor PER GROUP (measured 0.21x — profile_kv_transfer.py), so
        # gate on the measured floor.
        grouped = (not dev_ok
                   and self.runner.d2h_fetch_floor_ms() < 10.0 and n > 1)
        if quant:
            # Packed int8+scales parcel (engine/kv_quant.py): the wire
            # carries ~half the bf16 bytes — the disagg transfer tax
            # (PERF_NOTES, 15–20 ms/prompt on real attachments) halves
            # with it.
            from dynamo_tpu.engine.kv_quant import KV_SCALE_BYTES
            shape = [2, spec.num_layers, self.runner.canonical_nkv, n,
                     self.config.page_size, spec.head_dim + KV_SCALE_BYTES]
            meta = {"shape": shape, "dtype": "uint8"}
        else:
            shape = [2, spec.num_layers, self.runner.canonical_nkv, n,
                     self.config.page_size, spec.head_dim]
            meta = {"shape": shape, "dtype": "bfloat16"}
        if on_ticket is not None and not dev_ok and \
                self.runner.d2h_fetch_floor_ms() < 10.0:
            # Chunk-streamed path: stage BEFORE prefilling (the jax
            # device path can't stream — it registers one finished
            # device array — so it keeps the stage-after-prefill
            # order). Same per-group D2H floor gate as `grouped`: a
            # tunneled chip pays its ~100 ms RTT once per page group,
            # which would swamp the overlap win.
            return self._prefill_extract_streamed(req, plane, meta,
                                                  on_ticket)
        first_token, handle, prompt_len = self._prefill_for_extract(
            req, grouped=grouped)
        if grouped:
            groups = [(h[1], (lambda hh=h:
                              self.runner.finalize_extract(hh)))
                      for h in handle]
            ticket = plane.stage(meta=meta, resolve_groups=groups,
                                 prompt_len=prompt_len)
        else:
            ticket = plane.stage(
                meta=meta,
                resolve=lambda: self.runner.finalize_extract(handle),
                device_array=handle[0] if dev_ok else None,
                prompt_len=prompt_len)
        if on_ticket is not None:
            on_ticket(ticket)
        return first_token, ticket, prompt_len

    @staticmethod
    def _reject_adapter_extract(req: PreprocessedRequest) -> None:
        """Disaggregated prefill serves the BASE model only: the decode
        side keeps adapter requests local (llm/disagg.py gate), so an
        adapter reaching a prefill worker is a routing bug — fail typed
        rather than compute base KV under an adapter-salted hash chain."""
        if getattr(req, "adapter", None):
            from dynamo_tpu.runtime.errors import InvalidRequestError
            raise InvalidRequestError(
                f"disaggregated prefill does not serve LoRA adapter "
                f"requests (adapter={req.adapter!r}); the decode worker "
                f"prefills these locally")

    # Backstop for streamed-extract group resolvers: the plane thread
    # waits on the chunk's extract event at most this long before
    # failing the pull (an aborted prefill sets the events, so only a
    # wedged engine thread ever reaches it).
    STREAM_RESOLVE_TIMEOUT_S = 120.0

    def _prefill_extract_streamed(self, req: PreprocessedRequest, plane,
                                  meta: dict, on_ticket):
        """ENGINE-THREAD ONLY. Chunk-streamed disagg extract: stage the
        transfer ticket FIRST — one page group per prefill chunk, each
        gated on a threading.Event its extract dispatch sets — deliver
        it through ``on_ticket`` (the handler yields it to the decode
        worker immediately), THEN run the chunk loop. The plane thread
        streams group i to the sink while chunk i+1 is still computing,
        so by the time the first token resolves most of the parcel is
        already across the wire. A whole-prompt (non-chunked) plan
        degenerates to one group staged before its single dispatch —
        same contract, no special casing downstream.

        Failure mid-loop marks every pending group failed (resolvers
        raise, the sink's pull errors, the decode worker falls back to
        local prefill) and re-raises to the handler."""
        self._reject_adapter_extract(req)
        self._validate(req)
        r = _Request(req=req, ctx=Context(), out_q=None, loop=None,  # type: ignore[arg-type]
                     tokens_all=list(req.token_ids))
        plan = self._plan_prefill(r)
        if plan is None:
            raise RuntimeError("prefill worker KV pool exhausted")
        cfg = self.config
        page = cfg.page_size
        prompt = r.tokens_all
        max_chunk = min(cfg.max_prefill_tokens, cfg.prefill_buckets[-1])
        # Page-group boundaries are known at PLAN time: the reused
        # prefix extracts immediately; each chunk's pages extract as its
        # program dispatches (device-stream order: the gather reads the
        # chunk's writes).
        first_page = r.reuse_tokens // page
        bounds: list[tuple[int, int]] = []
        chunks: list[tuple[int, int, bool]] = []  # (start, n_tok, final)
        if first_page:
            bounds.append((0, first_page))
        start = r.reuse_tokens
        while start < len(prompt):
            n_tok = min(max_chunk, len(prompt) - start)
            bounds.append((start // page, -(-(start + n_tok) // page)))
            chunks.append((start, n_tok, start + n_tok >= len(prompt)))
            start += n_tok
        state: dict = {"handles": {}, "error": None}
        events = [threading.Event() for _ in bounds]
        timeout_s = self.STREAM_RESOLVE_TIMEOUT_S

        def _resolver(idx: int):
            def resolve():
                if not events[idx].wait(timeout=timeout_s):
                    raise RuntimeError(
                        f"streamed extract group {idx} never became "
                        "ready (prefill wedged?)")
                if state["error"] is not None:
                    raise RuntimeError(
                        f"chunked prefill failed: {state['error']}")
                return self.runner.finalize_extract(state["handles"][idx])
            return resolve

        groups = [(hi - lo, _resolver(i))
                  for i, (lo, hi) in enumerate(bounds)]
        ticket = plane.stage(meta=meta, resolve_groups=groups,
                             prompt_len=len(prompt))
        self.streamed_extracts += 1
        on_ticket(ticket)
        gi = 0
        try:
            if first_page:
                state["handles"][0] = self.runner.extract_pages_async(
                    r.pages[:first_page])
                events[0].set()
                gi = 1
            if plan != "chunked":
                # Whole-prompt plan: one dispatch, one streamed group.
                first_token = int(self.runner.prefill_batch([plan])[0])
                lo, hi = bounds[gi]
                state["handles"][gi] = self.runner.extract_pages_async(
                    r.pages[lo:hi])
                events[gi].set()
            else:
                first_token = None
                for ci, (c_start, n_tok, final) in enumerate(chunks):
                    seq = self._chunk_seq(r, c_start, n_tok, final)
                    if final:
                        pen = self._penalties_of(r)
                        rows = (self._count_row_of(r)[None]
                                if any(pen) else None)
                        first_token = int(self.runner.prefill_batch(
                            [seq], count_rows=rows)[0])
                    else:
                        self.runner.prefill_chunk_async(seq)
                    lo, hi = bounds[gi]
                    state["handles"][gi] = \
                        self.runner.extract_pages_async(r.pages[lo:hi])
                    events[gi].set()
                    gi += 1
            if not r.no_cache:
                for idx, h in enumerate(r.blocks.block_hashes):
                    self.allocator.register(r.pages[idx], h)
            return first_token, ticket, len(prompt)
        except BaseException as exc:
            # Pending resolvers must fail fast, not wait out the
            # backstop: mark, wake, re-raise to the handler.
            state["error"] = f"{type(exc).__name__}: {exc}"
            for ev in events:
                ev.set()
            raise
        finally:
            # Every extract is dispatched (or the parcel is failed):
            # device-stream order protects the pages, so release now —
            # same fencing argument as _prefill_for_extract.
            self.allocator.release(r.pages)
            r.pages = []

    def register_adapter(self, name: str, path: str | None = None,
                         weights: dict | None = None,
                         pin: bool = False) -> None:
        """Register a LoRA adapter (host-side: parse/pad/stack only —
        the device upload happens lazily at first use on the engine
        thread, which IS the hot-load path). Safe from any thread."""
        if self.adapters is None:
            raise RuntimeError(
                "engine built without adapters (config.max_adapters=0)")
        self.adapters.register(name, path=path, weights=weights)
        if pin:
            self.adapters.pin(name)

    # -- engine-thread adapter resolution -------------------------------------
    def _acquire_adapter(self, r: _Request) -> bool:
        """Resolve the request's adapter name to a resident device slot
        (hot-loading on miss — ENGINE THREAD). Returns False after
        pushing the typed error when resolution fails (unknown name ->
        404 at the frontend; all slots busy -> 503, router retries)."""
        name = getattr(r.req, "adapter", None)
        if not name:
            return True
        if r.adapter_ref is not None:
            return True  # already held (shouldn't happen, but idempotent)
        try:
            if self.adapters is None:
                from dynamo_tpu.runtime.errors import AdapterNotFoundError
                raise AdapterNotFoundError(
                    f"adapter {name!r} requested but this engine serves "
                    f"no adapters")
            r.adapter_slot = self.adapters.acquire(name)
        except Exception as exc:  # noqa: BLE001 — typed errors reach the stream
            r.push(exc)
            return False
        r.adapter_ref = name
        # Accounting attribution: scripts/slo_report.py --by adapter.
        r.ctx.values["adapter"] = name
        return True

    def _release_adapter(self, r: _Request | None) -> None:
        if r is not None and r.adapter_ref is not None \
                and self.adapters is not None:
            self.adapters.release(r.adapter_ref)
            r.adapter_ref = None
            r.adapter_slot = 0

    async def embed(self, token_lists: list[list[int]],
                    pooling: str = "last") -> list[list[float]]:
        """Batch embeddings, computed on the engine thread between windows
        (/v1/embeddings backend)."""
        out = await self.run_job(
            lambda: self.runner.embed(token_lists, pooling))
        return [row.tolist() for row in out]

    async def clear_kv_blocks(self) -> int:
        """Admin: drop the reusable (inactive) prefix cache; host tiers
        flush too. Returns pages freed in HBM."""
        def job():
            n = self.allocator.clear_inactive()
            if self.host_cache is not None:
                self.host_cache.clear()
            return n
        return await self.run_job(job)

    # -- KV observability (docs/OBSERVABILITY.md "KV & capacity") -------------
    def inventory_digest(self):
        """Compact what-KV-lives-here summary for the event plane
        (KvInventoryDigest): block counts per tier, capacity headroom,
        and a k-min sketch over every hash this worker can serve."""
        from dynamo_tpu.llm.kv_router.protocols import (KvInventoryDigest,
                                                        kmin_sketch)
        hashes = list(self.allocator.cached.keys())
        tier_blocks = {"g1": len(hashes)}
        if self.host_cache is not None:
            host_hashes = self.host_cache.block_hashes()
            tier_blocks["g2"] = len(host_hashes)
            hashes.extend(host_hashes)
            disk = self.host_cache.disk
            if disk is not None:
                with disk._lock:
                    disk_hashes = list(disk._index.keys())
                tier_blocks["g3"] = len(disk_hashes)
                hashes.extend(disk_hashes)
        return KvInventoryDigest(
            blocks=len(self.allocator.cached),
            tier_blocks=tier_blocks,
            pages_total=self.allocator.num_pages,
            pages_free=self.allocator.num_free,
            pages_active=self.allocator.num_active,
            sketch=kmin_sketch(hashes))

    def kv_status(self) -> dict:
        """The /debug/kv body for this worker (runtime/health.py):
        allocator occupancy/lifecycle counters, offload-tier stats, KV
        data plane + G4 remote-source telemetry, reuse attribution, and
        the current inventory digest."""
        onboard = self.onboard_blocks
        status = {
            "role": "engine",
            "allocator": self.allocator.stats(),
            "tiers": (self.host_cache.stats()
                      if self.host_cache is not None else {}),
            "reuse": {
                "prefix_hit_blocks": self.prefix_hit_blocks,
                "prefix_lookup_blocks": self.prefix_lookup_blocks,
                "onboard_blocks_host": onboard - self.g4_blocks,
                "onboard_blocks_peer": self.g4_blocks,
            },
            "plane": self.plane.stats() if self.plane is not None else None,
            "remote": (self.remote_source.stats()
                       if self.remote_source is not None else None),
            "kvbm": self.kvbm.status(),
            "adapters": (self.adapters.status()
                         if self.adapters is not None else None),
            "digest": self.inventory_digest().to_wire(),
        }
        return status

    def perf_status(self) -> dict:
        """The /debug/perf body for this worker (runtime/health.py;
        docs/OBSERVABILITY.md "Engine perf plane"): per-program compile
        stats from the process-global observatory, live window/roofline
        series, HBM gauges, and the runner's params/KV/workspace memory
        breakdown."""
        expected = self.config.expected_roofline_frac
        raw = os.environ.get("DTPU_EXPECTED_ROOFLINE_FRAC")
        if raw:
            expected = float(raw)
        compiles = self._perf.snapshot()
        status = {
            "role": "engine",
            "compiles": compiles,
            "window": self._perf.window_snapshot(),
            "roofline": {
                "weight_read_step_ms": round(self._step_floor_ms, 4),
                "frac": round(self._perf.roofline_frac, 4),
                "expected_frac": expected,
            },
            "hbm": self.runner.hbm_stats(),
            "memory": self.runner.memory_breakdown(),
        }
        if self.config.spec_decode:
            # Verify-of-k bandwidth: the spec program runs m_outer verify
            # steps of S = spec_k + 1 positions each, so cost-registry
            # bytes over m_outer * S is HBM bytes per VERIFIED position —
            # the number the fused multi-token verify keeps near the
            # single-token step's (one weight read covers S positions).
            cost = (compiles["programs"].get("spec_window") or {}).get(
                "cost") or {}
            positions = self.spec_m_outer * (self.config.spec_k + 1)
            vb = cost.get("bytes_accessed")
            status["spec"] = {
                "k": self.config.spec_k,
                "m_outer": self.spec_m_outer,
                "drafts": self.spec_drafts,
                "draft_tokens": self.spec_tokens,
                "accepted_tokens": self.spec_accepted,
                "acceptance_rate": round(
                    self.spec_accepted / self.spec_tokens, 4)
                if self.spec_tokens else None,
                # emit_hist[e] = verify steps that emitted e tokens
                # (0 = dispatched frozen, spec_k+1 = all drafts landed).
                "emit_hist": list(self.spec_emit_hist),
                "brownout_windows": self.spec_brownout_windows,
                "verify_bytes_per_token": round(vb / positions, 1)
                if vb and positions else None,
                "verify_cost_source": cost.get("source"),
            }
        return status

    def handler(self):
        async def handle(request, context):
            if isinstance(request, dict) and request.get("clear_kv_blocks"):
                freed = await self.clear_kv_blocks()
                yield {"cleared": freed}
                return
            if isinstance(request, dict) and request.get("embed"):
                vectors = await self.embed(request["token_lists"],
                                           request.get("pooling", "last"))
                yield {"embeddings": vectors}
                return
            async for out in self.generate(request, context):
                yield out

        return handle

    # -- engine thread --------------------------------------------------------
    def _warmup_window_programs(self) -> None:
        """Compile the decode-window program (smallest page-table bucket)
        and the smallest prefill bucket before serving — the runner
        compiles lazily per shape key on the engine thread, so without
        this the first request stalls on XLA compiles for both. Larger
        prefill buckets / page-table widths still compile on first use.
        Warmup work is inert: all-zero packed rows are inactive
        (PK_SEQLEN=0) and prefill rows write only the reserved scratch
        page 0."""
        t0 = time.monotonic()
        bucket_pages = self.runner.bucket_pages_for(1)
        packed = np.zeros((self.config.max_num_seqs,
                           PK_PREFIX + bucket_pages), np.int32)
        if self.config.spec_decode:
            # ONE spec program covers greedy, sampled and seeded verify:
            # temperature/top-k/top-p/seed are data (packed columns),
            # not trace-time specializations, so warming it once also
            # warms every sampling mix. (Penalties are rejected at
            # validation — no penalized variant exists to warm.)
            outs = self.runner.decode_spec_window(
                packed, self.spec_m_outer, self.config.spec_k)
            np.asarray(outs[0])
            log.info("warmed spec window program m=%d k=%d in %.1fs "
                     "(covers greedy + sampled + seeded verify)",
                     self.spec_m_outer, self.config.spec_k,
                     time.monotonic() - t0)
            t0 = time.monotonic()
            bucket = self.config.prefill_buckets[0]
            seq = PrefillSeq(tokens=np.zeros(min(4, bucket), np.int32),
                             start_pos=0,
                             chunk_pages=np.zeros(1, np.int32),
                             hist_pages=None, sampling=(0.0, 0, 1.0))
            self.runner.prefill_batch([seq])
            log.info("warmed prefill bucket %d in %.1fs", bucket,
                     time.monotonic() - t0)
            self._warmup_prefill_ladder()
            return
        outs = self.runner.decode_window(packed, self.decode_window)
        np.asarray(outs[0])  # force compile + execute
        # The penalized variant too: a first penalized request must not
        # stall every in-flight stream on its compile. One inactive row
        # with penalty bits set selects it; inactive rows do no work.
        packed_pen = packed.copy()
        packed_pen[0, PK_FREQPEN] = np.float32(1.0).view(np.int32)
        # TWICE: under tp > 1, GSPMD re-shards counts_dev in the first
        # penalized program's output (replicated P() in, vocab-sharded
        # out), so the SECOND call traces a new input signature — warm
        # both here or the first real penalized request still pays that
        # second compile (found by the perf plane's recompile detector).
        for _ in range(2):
            outs = self.runner.decode_window(packed_pen, self.decode_window)
            np.asarray(outs[0])
        packed_seed = packed.copy()
        packed_seed[0, PK_SEEDED] = 1
        outs = self.runner.decode_window(packed_seed, self.decode_window)
        np.asarray(outs[0])
        packed_both = packed_seed.copy()
        packed_both[0, PK_FREQPEN] = np.float32(1.0).view(np.int32)
        for _ in range(2):
            outs = self.runner.decode_window(packed_both, self.decode_window)
            np.asarray(outs[0])
        log.info("warmed window programs M=%d in %.1fs", self.decode_window,
                 time.monotonic() - t0)
        t0 = time.monotonic()
        bucket = self.config.prefill_buckets[0]
        seq = PrefillSeq(tokens=np.zeros(min(4, bucket), np.int32),
                         start_pos=0,
                         chunk_pages=np.zeros(1, np.int32),  # scratch page
                         hist_pages=None, sampling=(0.0, 0, 1.0))
        self.runner.prefill_batch([seq])  # slots=None blocks until done
        log.info("warmed prefill bucket %d in %.1fs", bucket,
                 time.monotonic() - t0)
        self._warmup_prefill_ladder()

    def _warmup_prefill_ladder(self) -> None:
        """Pre-compile EVERY prefill bucket, with and without history
        (config.warmup_prefill_ladder): larger buckets otherwise compile
        on first use — the first long prompt then pays seconds of XLA
        compile per bucket while every live decode slot waits (the
        BENCH_r05 13.7 s TTFT-p99 outlier round). Warmup rows are inert:
        zero tokens, all writes to the reserved scratch page 0. jit
        COMPILATION blocks the caller, so each call here really pays
        (and logs) its compile; the inert executions drain async."""
        if not self.config.warmup_prefill_ladder:
            return
        page = self.config.page_size
        for bucket in self.config.prefill_buckets:
            for with_h in (False, True):
                t0 = time.monotonic()
                seq = PrefillSeq(
                    tokens=np.zeros(bucket, np.int32),
                    start_pos=page if with_h else 0,
                    chunk_pages=np.zeros(1, np.int32),
                    hist_pages=(np.zeros(1, np.int32) if with_h
                                else None),
                    sampling=(0.0, 0, 1.0))
                self.runner.prefill_batch([seq], fetch=False)
                log.info("warmed prefill bucket %d%s in %.1fs", bucket,
                         " +history" if with_h else "",
                         time.monotonic() - t0)

    def _engine_loop(self) -> None:
        log.info("engine loop starting (slots=%d pages=%d window=%d)",
                 self.config.max_num_seqs, self.runner.num_pages,
                 self.decode_window)
        if self.config.warmup_windows:
            try:
                self._warmup_window_programs()
            except Exception:  # noqa: BLE001 — warmup is best-effort
                log.exception("window warmup failed; compiling lazily")
        # Perf plane warmup boundary: compiles past here show up in the
        # pane as post-warmup (larger buckets still compile lazily and
        # legitimately; only SAME-signature recompiles are flagged).
        self._perf.mark_ready()
        depth = max(1, self.config.pipeline_depth)
        while self._running:
            if chaos.ACTIVE:
                # Chaos site "engine": engine.stall_ms freezes the loop
                # thread mid-iteration — the observable effect is a real
                # decode-dispatch gap (decode_stall_seconds tail) which
                # the flight-recorder anomaly trigger must catch.
                stall = chaos.value("engine.stall_ms", "engine")
                if stall is not None:
                    time.sleep(stall / 1e3)
            self._run_jobs()
            self._resolve_ready_first()
            self._resolve_spills()
            self._maintain_kvbm()
            self._retire_chunks()
            try:
                admitted = self._admit()
            except Exception:  # noqa: BLE001
                log.exception("admission failed")
                admitted = False
            # Stall-free chunked prefill: at most prefill_chunk_tokens of
            # chunk work BEFORE the decode window, so a long prompt's
            # interference with live decode slots is bounded by ~one
            # chunk's compute per window instead of the whole prompt.
            chunk_dispatched = self._dispatch_prefill_chunks()
            have_active = any(r is not None and not r.prefilling
                              for r in self.slot_req)
            dispatched = False
            if have_active and len(self._inflight) < depth:
                now = time.monotonic()
                if self._last_decode_dispatch is not None:
                    gap = now - self._last_decode_dispatch
                    self.decode_stall_max_s = max(self.decode_stall_max_s,
                                                  gap)
                    if self.m_decode_stall is not None:
                        self.m_decode_stall.observe(gap)
                    self._flight_stall_last = max(self._flight_stall_last,
                                                  gap)
                    if (flight.stall_threshold_s
                            and gap >= flight.stall_threshold_s):
                        # Decode-stall tail spike: freeze the flight ring
                        # and capture a diagnostic bundle (throttled).
                        flight.trigger(f"decode_stall_{gap:.2f}s")
                self._last_decode_dispatch = now
                try:
                    window = self._dispatch_window()
                except Exception as exc:  # noqa: BLE001 — fail all, keep serving
                    log.exception("decode window dispatch failed")
                    for i, r in enumerate(self.slot_req):
                        if r is not None and not r.prefilling:
                            r.push(RuntimeError(f"engine step failed: {exc}"))
                            self._finish_slot(i, register=False)
                else:
                    if window.toks is None:
                        # No device work (every live slot frozen): handle
                        # the preemption records immediately.
                        self._do_process(window)
                    else:
                        self._inflight.append(window)
                        dispatched = True
            elif not have_active:
                self._last_decode_dispatch = None
            # Process the oldest window once the pipe is full (or drain it
            # when nothing new can be dispatched).
            if self._inflight and (len(self._inflight) >= depth
                                   or not dispatched):
                window = self._inflight.popleft()
                self._do_process(window)
                self.step_count += 1
                self._publish()
                self._note_flight(window)
            self._release_ready_pages()
            if self._inflight or chunk_dispatched:
                continue  # device busy; windows/chunks pace the loop
            if not have_active and self._chunk_inflight:
                # Prefill-only phase at full chunk depth: block on the
                # oldest chunk program instead of spinning.
                self._retire_chunks(block=True)
            elif self._pending_first:
                # Nothing left on the device but first tokens unfetched
                # (e.g. a lone max_tokens=1 request): block on them now.
                self._resolve_ready_first(force=True)
            elif not admitted and not have_active and not self._prefilling:
                self._resolve_spills(force=True)
                time.sleep(0.002)  # fully idle

    # -- KV tiering (G2/G3 offload + onboard) ---------------------------------
    @property
    def remote_source(self):
        """G4 remote tier (kv_plane.RemoteBlockSource, set by the worker
        main once the KV plane is up). Lives on the KVBM so the peer
        tier is part of the one placement-policy object; this property
        keeps every existing call site working."""
        return self.kvbm.remote_source

    @remote_source.setter
    def remote_source(self, source) -> None:
        self.kvbm.remote_source = source

    def _maintain_kvbm(self) -> None:
        """Watermark sweep, once per engine-loop iteration: proactive
        LRU demotions queue their extracts through the evict hook; the
        flush dispatches them before any later program can overwrite
        the freed pages."""
        if self.kvbm.maintain():
            self._flush_spills()

    def _to_local_parcel(self, kv):
        """Convert a KV block to this worker's parcel form: packed
        int8+scales (uint8) when the pool is quantized, bf16 otherwise
        (engine/kv_quant.py codec; mixed-dtype fleets interoperate)."""
        from dynamo_tpu.engine.kv_quant import (parcel_to_bf16,
                                                parcel_to_packed)
        if self.runner.quant_kv == "int8":
            return parcel_to_packed(kv)
        return parcel_to_bf16(kv)

    def _on_evict(self, block_hash: int, page: int) -> None:
        self._evict_buffer.append((block_hash, page))

    def _flush_spills(self) -> None:
        """Dispatch one batched extract for pages evicted since the last
        flush. MUST run before any program that writes KV pages (the
        device stream then orders the read before the overwrite); the host
        fetch resolves asynchronously."""
        if not self._evict_buffer:
            return
        batch, self._evict_buffer = self._evict_buffer, []
        hashes = [h for h, _ in batch]
        pages = [p for _, p in batch]
        try:
            handle = self.runner.extract_pages_async(pages)
        except Exception:  # noqa: BLE001 — offload is best-effort
            log.exception("spill extract failed; blocks dropped from tiers")
            return
        self._pending_spills.append({"handle": handle, "hashes": hashes})

    def _resolve_spills(self, force: bool = False) -> None:
        if not self._pending_spills or self.host_cache is None:
            return
        for entry in list(self._pending_spills):
            dev, _ = entry["handle"]
            if isinstance(dev, tuple):  # quantized extract: (data, scale)
                dev = dev[0]
            ready = getattr(dev, "is_ready", lambda: True)()
            if not (ready or force):
                continue
            self._pending_spills.remove(entry)
            try:
                kv = self.runner.finalize_extract(entry["handle"])
            except Exception:  # noqa: BLE001
                log.exception("spill fetch failed; blocks dropped")
                continue
            for i, h in enumerate(entry["hashes"]):
                self.kvbm.offload(h, kv[:, :, :, i])

    def _try_onboard(self, r: _Request, hashes: list[int],
                     cached_pages: list[int]) -> tuple[list[int], int, int]:
        """Extend the G1 prefix hit with consecutive G2/G3 blocks — and
        past those, G4 blocks fetched from peer workers' host tiers —
        uploading them into fresh pages (re-registered for sharing)
        instead of recomputing. Returns (extra_pages, extra_tokens,
        peer_tokens) — peer_tokens is the G4 share of extra_tokens, for
        per-request tier attribution."""
        page = self.config.page_size
        if self.host_cache is None and self.remote_source is None:
            return [], 0, 0
        # Never reuse past the second-to-last block (the last token must
        # always be recomputed for logits), matching the G1 rule.
        allowed = (len(r.tokens_all) - 1) // page - len(cached_pages)
        if allowed <= 0:
            return [], 0, 0
        # KVBM tier walk: host/disk first, then one bounded peer consult
        # (engine/kvbm.py owns the policy; device uploads stay here).
        blocks, n_peer = self.kvbm.onboard_walk(
            hashes, len(cached_pages), allowed, trace_id=r.ctx.trace_id)
        if n_peer:
            n_host = len(blocks) - n_peer
            normalized = []
            for h, kv in blocks[n_host:]:
                # Peers may run the other KV dtype: normalize fetched
                # blocks to THIS worker's parcel form (packed uint8 for
                # int8 pools, bf16 otherwise) so tier entries and the
                # onboard stack below stay uniform.
                kv = self._to_local_parcel(kv)
                normalized.append((h, kv))
                if self.host_cache is not None:
                    # Promote into the local G2 so the next hit is one
                    # NIC hop shorter.
                    self.host_cache.put(h, kv, promotion=True)
            blocks = blocks[:n_host] + normalized
            self.g4_blocks += n_peer
        if not blocks:
            return [], 0, 0
        pages = self.allocator.allocate(len(blocks))
        if pages is None:
            return [], 0, 0
        self._flush_spills()  # the allocation may itself have evicted
        stacked = np.stack([kv for _, kv in blocks], axis=3)
        try:
            self.runner.insert_pages(stacked, pages)
        except Exception:  # noqa: BLE001
            log.exception("onboard upload failed; recomputing instead")
            self.allocator.release(pages)
            return [], 0, 0
        for (h, _), p in zip(blocks, pages):
            self.allocator.register(p, h)
        self.onboard_blocks += len(blocks)
        self.kvbm.note_promoted(len(blocks) - n_peer, n_peer,
                                trace_id=r.ctx.trace_id)
        return pages, len(blocks) * page, n_peer * page

    def _release_ready_pages(self) -> None:
        """Release deferred pages whose potential writers are done. An
        entry (s, pages) may still be scattered to by any window with
        device work dispatched at-or-before serial s; windows process in
        serial order, so the fence is just below the oldest in-flight
        window (everything, if none are in flight — toks=None windows
        never carry device work and never enter the deque)."""
        if not self._pending_release:
            return
        fence = (self._inflight[0].serial - 1 if self._inflight
                 else self._dispatch_serial)
        keep = []
        for serial, pages in self._pending_release:
            if serial <= fence:
                self.allocator.release(pages)
            else:
                keep.append((serial, pages))
        self._pending_release = keep

    def _resolve_ready_first(self, force: bool = False) -> None:
        for entry in list(self._pending_first):
            handle = entry["handle"]["tokens"]
            ready = getattr(handle, "is_ready", lambda: True)()
            if not (ready or force):
                continue
            self._pending_first.remove(entry)
            self._resolve_first(entry)

    def _force_resolve_first_for(self, slots_needed: set[int]) -> None:
        """Block on the fetches whose first tokens the caller is about to
        need (their windows are being processed — the fetch predates those
        windows' compute, so it is effectively ready)."""
        for entry in list(self._pending_first):
            if any(slot in slots_needed and self.slot_req[slot] is r
                   for _, r, slot, _ in entry["rows"]):
                self._pending_first.remove(entry)
                self._resolve_first(entry)

    def _resolve_first(self, entry: dict) -> None:
        cold = entry.get("cold", 0)
        if cold:
            # The batch's cold tokens leave the SLA ledger, and its
            # dispatch->readback interval calibrates the projection rate
            # (end-to-end: queueing behind decode windows is priced in).
            self._cold_inflight -= cold
            self._prefill_rate_sample(
                cold, time.monotonic() - entry.get("t0", 0.0))
        h = entry["handle"]
        want_lp = any(r.req.sampling_options.logprobs is not None
                      for _, r, _, _ in entry["rows"])
        try:
            vals = np.asarray(h["tokens"])
            lps = np.asarray(h["lp"]) if want_lp else None
            top_vs = np.asarray(h["top_v"]) if want_lp else None
            top_is = np.asarray(h["top_i"]) if want_lp else None
        except Exception as exc:  # noqa: BLE001 — device fault at fetch
            log.exception("first-token fetch failed")
            for _, r, slot, epoch in entry["rows"]:
                if self.slot_req[slot] is r and r.epoch == epoch:
                    r.push(RuntimeError(f"prefill readback failed: {exc}"))
                    self._finish_slot(slot, register=False)
            return
        t1 = time.monotonic()
        t0 = entry.get("t0")
        if t0:
            # Batched-prefill phase: dispatch -> first-token readback.
            if self.phase is not None:
                self.phase.prefill.observe(t1 - t0)
            rec = self._recorder
            if rec.enabled:
                for _, r, slot, epoch in entry["rows"]:
                    if self.slot_req[slot] is r and r.epoch == epoch:
                        rec.add("engine.prefill", r.ctx.trace_id,
                                r.ctx.span_id, t0, t1,
                                attrs={"prompt_tokens":
                                       len(r.req.token_ids),
                                       "reuse_tokens": r.reuse_tokens,
                                       "chunked": bool(
                                           entry.get("chunked"))})
        for row, r, slot, epoch in entry["rows"]:
            if self.slot_req[slot] is not r or r.epoch != epoch:
                continue  # slot reassigned (failure path already notified)
            tok = int(vals[row])
            r.generated += 1
            finish = self._check_finish(r, tok)
            lp_out = None
            if r.req.sampling_options.logprobs is not None:
                k = r.req.sampling_options.logprobs or 0
                lp_out = ([float(lps[row])],
                          [[{"token_id": int(top_is[row, j]),
                             "logprob": float(top_vs[row, j])}
                            for j in range(k)]])
            self._emit(r, [tok], finish, lp_out)
            r.last_token = tok
            r.tokens_all.append(tok)
            if finish is not None:
                self._finish_slot(slot, register=True)

    def _do_process(self, w: _Window) -> None:
        try:
            self._process_window(w)
        except Exception as exc:  # noqa: BLE001
            # Device faults surface at the readback: host token state has
            # diverged from the on-device chain, so fail every request this
            # window covered rather than continue with silently-wrong
            # streams/prefix hashes.
            log.exception("window processing failed")
            for i, snap in enumerate(w.slots):
                if snap is not None and self.slot_req[i] is snap[0]:
                    snap[0].push(RuntimeError(
                        f"window processing failed: {exc}"))
                    self._finish_slot(i, register=False)

    # -- engine-local brownout -------------------------------------------------
    def _update_brownout(self) -> None:
        """Pressure level 0..3 from the projected-TTFT/budget ratio —
        the engine-local analogue of the frontend limiter's
        pressure_level() (runtime/overload.py). Level >=
        brownout_spec_disable_level suspends speculative drafting: under
        prefill backlog the verify steps' extra positions are pure decode
        overhead whenever drafts stop being accepted."""
        cfg = self.config
        projected = (self.estimated_ttft_ms()
                     if cfg.ttft_budget_ms else None)
        if not projected:
            self.brownout_level = 0
            return
        ratio = projected / cfg.ttft_budget_ms
        self.brownout_level = (0 if ratio < 1.0 else
                               1 if ratio < 1.5 else
                               2 if ratio < 2.5 else 3)

    # -- admission / prefill --------------------------------------------------
    def _admit(self) -> bool:
        self._update_brownout()
        free_slots = [i for i, r in enumerate(self.slot_req) if r is None]
        staged: list[tuple[_Request, int, PrefillSeq]] = []
        while free_slots:
            if self._deferred_head is not None:
                r, self._deferred_head = self._deferred_head, None
            else:
                try:
                    r = self.waiting.get_nowait()
                except queue.Empty:
                    break
            self._queue_pop_accounting(r)
            if r.ctx.is_killed or r.ctx.is_stopped:
                r.push(LLMEngineOutput(
                    token_ids=[], finish_reason=FinishReason.CANCELLED).to_wire())
                continue
            # Adapter resolution first (engine thread: the hot-load is
            # device work): a missing adapter 404s here, a slot-starved
            # store 503s — either way before any pages are touched.
            if not self._acquire_adapter(r):
                continue
            if r.injected is not None:
                self._note_queue_wait(r)
                slot = free_slots.pop(0)
                try:
                    if self._admit_injected(r, slot):
                        continue
                except Exception as exc:  # noqa: BLE001
                    log.exception("KV injection failed")
                    r.push(RuntimeError(f"kv injection failed: {exc}"))
                    free_slots.insert(0, slot)
                    self._release_adapter(r)
                    continue
                # No pages for the transferred KV: fall back to a normal
                # local prefill of the full prompt (correctness preserved).
                free_slots.insert(0, slot)
                r.injected = None
            if (self.config.ttft_budget_ms and self._cold_inflight > 0
                    and self.prefill_rate_tok_s):
                # SLA gate: admitting this prompt must not push the
                # projected prefill backlog past the TTFT budget. With
                # nothing cold in flight the head always admits (an
                # over-budget single prompt must not starve).
                projected = ((self._cold_inflight + len(r.tokens_all))
                             / self.prefill_rate_tok_s * 1e3)
                if projected > self.config.ttft_budget_ms:
                    # Park at the HEAD (strict FIFO): re-queueing at the
                    # tail would let later small prompts starve this one.
                    r.queued_cold = len(r.tokens_all)
                    with self._queue_stats_lock:
                        self._waiting_cold += r.queued_cold
                        self.num_waiting += 1
                    self._deferred_head = r
                    self.admission_deferred += 1
                    break
            self._note_queue_wait(r)
            try:
                plan = self._plan_prefill(r)
            except Exception as exc:  # noqa: BLE001
                log.exception("prefill planning failed")
                r.push(RuntimeError(f"prefill failed: {exc}"))
                self._release_adapter(r)
                continue
            if plan is None:
                # No KV room: put back and stop admitting (drop the
                # adapter ref while queued so it can't pin the slot).
                self._release_adapter(r)
                self._queue_put(r)
                break
            slot = free_slots.pop(0)
            if plan == "chunked":
                # Stall-free chunked prefill: the long prompt becomes
                # SCHEDULED chunk work interleaved with decode windows
                # (_dispatch_prefill_chunks) instead of a blocking loop.
                # The slot and all pages are held now; decode windows
                # skip the slot until the final chunk places it.
                r.cold_tokens = len(r.tokens_all) - r.reuse_tokens
                self._cold_inflight += r.cold_tokens
                r.prefilling = True
                r.prefill_pos = r.reuse_tokens
                r.prefill_t0 = time.monotonic()
                r.slot = slot
                self.slot_req[slot] = r
                self.disp_positions[slot] = 0
                self.disp_seq_lens[slot] = 0
                self.overrides.pop(slot, None)
                self._prefilling.append(r)
                continue
            r.cold_tokens = len(r.tokens_all) - r.reuse_tokens
            self._cold_inflight += r.cold_tokens
            staged.append((r, slot, plan))
        if not staged:
            return False
        # Batch the staged whole-prompt rows (split by history-ness; the
        # history variant costs a full-maxp gather per row).
        for with_h in (False, True):
            group = [(r, s, p) for (r, s, p) in staged
                     if (p.hist_pages is not None) == with_h]
            while group:
                chunk, group = group[:8], group[8:]
                rows = None
                if any(any(self._penalties_of(r)) for r, _, _ in chunk):
                    rows = np.stack([self._count_row_of(r)
                                     for r, _, _ in chunk])
                try:
                    handle = self.runner.prefill_batch(
                        [p for _, _, p in chunk],
                        slots=[s for _, s, _ in chunk],
                        count_rows=rows)
                except Exception as exc:  # noqa: BLE001
                    log.exception("batched prefill failed")
                    for r, _, _ in chunk:
                        self._cold_inflight -= r.cold_tokens
                        r.cold_tokens = 0
                        self.allocator.release(r.pages)
                        r.pages = []
                        self._release_adapter(r)
                        r.push(RuntimeError(f"prefill failed: {exc}"))
                    continue
                rows = []
                for row, (r, slot, _) in enumerate(chunk):
                    self._place_in_slot_pending(r, slot)
                    rows.append((row, r, slot, r.epoch))
                if self.runner.hist_dev is not None:
                    # Spec decode: full prompts (including any reused
                    # prefix; tokens_all also covers requeued requests'
                    # generated tokens) into the on-device draft
                    # history; the chained first token rides from
                    # tokens_dev.
                    self.runner.seed_history([
                        (slot, np.asarray(r.tokens_all, np.int32), 0,
                         True, None) for r, slot, _ in chunk])
                # First tokens are already chained on-device (tokens_dev);
                # their host values arrive asynchronously.
                self._pending_first.append({
                    "handle": handle, "rows": rows,
                    "cold": sum(r.cold_tokens for r, _, _ in chunk),
                    "t0": time.monotonic()})
        return True

    def _admit_injected(self, r: _Request, slot: int) -> bool:
        """Place a remotely-prefilled request: allocate pages, upload the
        transferred KV, start decoding at its first token. Returns False if
        the pool has no room (caller falls back to local prefill)."""
        page = self.config.page_size
        first_token, kv = r.injected
        prompt = r.tokens_all
        from dynamo_tpu.llm.tokens import chain_salt
        r.blocks = TokenBlockSequence(
            page, prompt, salt=chain_salt(getattr(r.req, "adapter", None)))
        total_pages = -(-len(prompt) // page)
        if kv.shape[3] != total_pages:
            raise ValueError(
                f"transferred KV has {kv.shape[3]} pages, prompt needs "
                f"{total_pages}")
        pages = self.allocator.allocate(total_pages)
        if pages is None:
            return False
        self._flush_spills()
        self.runner.insert_pages(kv, pages)
        r.pages = pages
        r.injected = None
        if self.runner.hist_dev is not None:
            # No local prefill ran, so the draft history and position
            # seed from host values (first_token is known here).
            self.runner.seed_history([
                (slot, np.asarray(prompt, np.int32), 0, True,
                 int(first_token))])
        self._place_in_slot(r, slot, first_token)
        return True

    def _plan_prefill(self, r: _Request):
        """Pin cached prefix pages + allocate the rest. Returns a PrefillSeq
        (whole-prompt row), "chunked" (long prompt; caller runs the chunk
        loop), or None (no KV room)."""
        cfg = self.config
        page = cfg.page_size
        prompt = r.tokens_all
        # Adapter-conditioned KV must never alias base (or other-adapter)
        # KV: the same tokens forwarded through adapter A produce
        # different K/V, so the hash chain roots at the adapter's salt —
        # prefix reuse, onboarding tiers and KV events all stay correct
        # per adapter with zero extra bookkeeping (llm/tokens.py).
        from dynamo_tpu.llm.tokens import chain_salt
        salt = chain_salt(getattr(r.req, "adapter", None))
        r.blocks = TokenBlockSequence(page, prompt, salt=salt)
        hashes = r.blocks.block_hashes
        mm = getattr(r.req, "mm_embeds", None)
        if mm:
            r.no_cache = True
            return self._plan_prefill_multimodal(r, mm)
        # Exact-reproduction contract for seeded sampling (temperature
        # > 0, tests/test_seeded_sampling.py): prefix reuse changes
        # WHICH program computes the non-reused tail (with-history
        # buckets vs the whole/chunked-prompt path), and the low-bit
        # logit differences flip near-ties under temperature sampling —
        # the same (prompt, seed) would emit different tokens depending
        # on what happens to be cached. First admission therefore
        # always takes the canonical no-reuse path; preemption
        # recompute (r.generated > 0) keeps reuse, because the pages it
        # finds are the original run's own bit-identical history.
        s = r.req.sampling_options
        canonical = (getattr(s, "seed", None) is not None
                     and (s.temperature or 0.0) > 0.0
                     and r.generated == 0)
        cached_pages = ([] if canonical
                        else self.allocator.acquire_cached(hashes))
        reuse_tokens = len(cached_pages) * page
        if reuse_tokens >= len(prompt):
            # Always recompute at least the last token so we have logits.
            drop = (reuse_tokens - len(prompt)) // page + 1
            self.allocator.release(cached_pages[len(cached_pages) - drop:])
            cached_pages = cached_pages[:len(cached_pages) - drop]
            reuse_tokens = len(cached_pages) * page
        self.prefix_lookup_blocks += max(1, len(hashes))
        self.prefix_hit_blocks += len(cached_pages)
        hbm_tokens = reuse_tokens
        # Extend the prefix from the host tiers (G2/G3) before recomputing.
        extra_pages, extra_tokens, peer_tokens = (
            ([], 0, 0) if canonical
            else self._try_onboard(r, hashes, cached_pages))
        cached_pages = cached_pages + extra_pages
        reuse_tokens += extra_tokens
        r.reuse_tokens = reuse_tokens
        # Accounting attribution (in-process pipelines: the frontend's
        # ctx IS this ctx, so the ledger record picks these up), incl.
        # which tier served the reuse — the "was the cache cold, and
        # where" signal scripts/slo_report.py rolls up per tenant.
        r.ctx.values["reuse_tokens"] = reuse_tokens
        r.ctx.values["kv_hit_ratio"] = (
            round(reuse_tokens / len(prompt), 4) if prompt else 0.0)
        r.ctx.values["kv_tiers"] = {
            "hbm": hbm_tokens,
            "host": extra_tokens - peer_tokens,
            "peer": peer_tokens}
        total_prompt_pages = -(-len(prompt) // page)
        need = total_prompt_pages - len(cached_pages)
        new_pages = self.allocator.allocate(need)
        if new_pages is None:
            self.allocator.release(cached_pages)
            return None
        r.pages = cached_pages + new_pages
        # Any evictions the allocations above caused must be extracted
        # before the prefill program overwrites those pages.
        self._flush_spills()
        rest = len(prompt) - reuse_tokens
        max_chunk = min(cfg.max_prefill_tokens, cfg.prefill_buckets[-1])
        if rest > max_chunk:
            return "chunked"
        first_page = reuse_tokens // page
        chunk_pages = np.asarray(r.pages[first_page:], np.int32)
        hist = (np.asarray(r.pages[:first_page], np.int32)
                if first_page else None)
        return PrefillSeq(
            tokens=np.asarray(prompt[reuse_tokens:], np.int32),
            start_pos=reuse_tokens, chunk_pages=chunk_pages,
            hist_pages=hist, sampling=self._sampling_of(r),
            logprobs=r.req.sampling_options.logprobs is not None,
            penalties=self._penalties_of(r), seed=self._seed_of(r),
            adapter_id=r.adapter_slot)

    def _plan_prefill_multimodal(self, r: _Request, mm: list[dict]):
        """Plan a prompt with encoder-embedding spans (reference
        multimodal processor role): no prefix reuse or onboarding
        (placeholder ids under spans don't content-hash the media).
        Prompts longer than one bucket take the chunked path — each chunk
        carries its slice of the embedding buffer — so a preempted
        multimodal request recomputes like any other. Returns a
        PrefillSeq, "chunked", or None (no KV room)."""
        cfg = self.config
        page = cfg.page_size
        prompt = r.tokens_all
        n = len(prompt)
        emb = np.zeros((n, self.runner.spec.hidden_size), np.float32)
        mask = np.zeros((n,), bool)
        for span in mm:
            start = int(span["start"])
            arr = np.frombuffer(span["b"], dtype=span.get(
                "dtype", "float32")).reshape(span["shape"])
            if start < 0 or start + arr.shape[0] > n:
                raise ValueError(
                    f"multimodal span [{start}, {start + arr.shape[0]}) "
                    f"outside the {n}-token prompt")
            if arr.shape[1] != emb.shape[1]:
                raise ValueError(
                    f"multimodal embedding width {arr.shape[1]} != model "
                    f"hidden size {emb.shape[1]}")
            emb[start:start + arr.shape[0]] = arr
            mask[start:start + arr.shape[0]] = True
        r.mm_buf = (emb, mask)
        self.prefix_lookup_blocks += max(1, len(r.blocks.block_hashes))
        total_pages = -(-n // page)
        pages = self.allocator.allocate(total_pages)
        if pages is None:
            return None
        r.pages = pages
        r.reuse_tokens = 0
        self._flush_spills()
        if n > min(cfg.max_prefill_tokens, cfg.prefill_buckets[-1]):
            return "chunked"
        return PrefillSeq(
            tokens=np.asarray(prompt, np.int32), start_pos=0,
            chunk_pages=np.asarray(pages, np.int32), hist_pages=None,
            sampling=self._sampling_of(r),
            logprobs=r.req.sampling_options.logprobs is not None,
            penalties=self._penalties_of(r), seed=self._seed_of(r),
            embeds=emb, embeds_mask=mask, adapter_id=r.adapter_slot)

    # -- stall-free chunked prefill -------------------------------------------
    def _chunk_seq(self, r: _Request, start: int, n: int,
                   final: bool) -> PrefillSeq:
        """One chunk row of ``r``'s prompt at [start, start+n). Penalty/
        seed/logprob state matters only for the FINAL chunk — earlier
        chunks' sampled tokens are discarded, so they take the cheapest
        (greedy, common-variant) program."""
        page = self.config.page_size
        first_page = start // page
        chunk_pages = np.asarray(
            r.pages[first_page:first_page + (-(-n // page))], np.int32)
        hist = np.asarray(r.pages[:first_page], np.int32)
        emb = emb_mask = None
        if r.mm_buf is not None:
            full_emb, full_mask = r.mm_buf
            sl = full_mask[start:start + n]
            if sl.any():
                emb, emb_mask = full_emb[start:start + n], sl
        tokens = np.asarray(r.tokens_all[start:start + n], np.int32)
        if not final:
            return PrefillSeq(
                tokens=tokens, start_pos=start, chunk_pages=chunk_pages,
                hist_pages=hist if len(hist) else None,
                sampling=(0.0, 0, 1.0), embeds=emb, embeds_mask=emb_mask,
                adapter_id=r.adapter_slot)
        return PrefillSeq(
            tokens=tokens, start_pos=start, chunk_pages=chunk_pages,
            hist_pages=hist if len(hist) else None,
            sampling=self._sampling_of(r),
            logprobs=r.req.sampling_options.logprobs is not None,
            penalties=self._penalties_of(r), seed=self._seed_of(r),
            embeds=emb, embeds_mask=emb_mask, adapter_id=r.adapter_slot)

    def _dispatch_prefill_chunks(self) -> bool:
        """One scheduling pass over the prefilling requests: dispatch at
        most ``prefill_chunk_tokens`` of chunk work, shared fairly
        oldest-first (each request's slice rounds down to page alignment
        — non-final chunks must end on a page boundary). Chunk programs
        in flight are bounded by pipeline_depth, like decode windows.
        Returns True when anything was dispatched. ENGINE THREAD."""
        if not self._prefilling:
            return False
        page = self.config.page_size
        depth = max(1, self.config.pipeline_depth)
        max_chunk = min(self.config.max_prefill_tokens,
                        self.config.prefill_buckets[-1])
        budget = self.prefill_chunk_tokens
        dispatched = False
        queue_snap = sorted(self._prefilling, key=lambda x: x.enqueue_t)
        for idx, r in enumerate(queue_snap):
            if budget < page or len(self._chunk_inflight) >= depth:
                break
            if r.ctx.is_killed or r.ctx.is_stopped:
                self._abort_prefilling(r, finish=FinishReason.CANCELLED)
                continue
            share = max(page, budget // (len(queue_snap) - idx))
            remaining = len(r.tokens_all) - r.prefill_pos
            n = min(share, max_chunk, remaining)
            final = n >= remaining
            if not final:
                n = (n // page) * page
                if n <= 0:
                    continue
            try:
                self._dispatch_one_chunk(r, n, final)
            except Exception as exc:  # noqa: BLE001
                log.exception("chunk prefill dispatch failed")
                self._abort_prefilling(r, error=exc)
                continue
            budget -= n
            dispatched = True
        if self.m_chunks_inflight is not None:
            self.m_chunks_inflight.set(len(self._chunk_inflight))
        return dispatched

    def _dispatch_one_chunk(self, r: _Request, n: int, final: bool) -> None:
        start = r.prefill_pos
        seq = self._chunk_seq(r, start, n, final)
        t0 = time.monotonic()
        if not final:
            # Intermediate chunk: KV state chains ON DEVICE; no host
            # readback of any kind (not even an async copy).
            arr = self.runner.prefill_chunk_async(seq)
            self._chunk_inflight.append(
                {"arr": arr, "r": r, "tokens": n, "t0": t0, "start": start})
            r.prefill_pos = start + n
            self._note_chunk_dispatch(n)
            return
        # Final chunk: a 1-row batched prefill — the sampled first token
        # is scattered into tokens_dev[slot] on device (decode windows
        # chain from it with no override) and its host value resolves
        # asynchronously through the _pending_first machinery.
        pen = self._penalties_of(r)
        rows = self._count_row_of(r)[None] if any(pen) else None
        slot = r.slot
        handle = self.runner.prefill_batch([seq], slots=[slot],
                                           count_rows=rows)
        self._place_in_slot_pending(r, slot)
        if self.runner.hist_dev is not None:
            # Spec decode: seed the on-device draft history with the full
            # accumulated tokens; the chained first token rides from
            # tokens_dev (dispatched after the scatter above).
            self.runner.seed_history([
                (slot, np.asarray(r.tokens_all, np.int32), 0, True, None)])
        self._prefilling.remove(r)
        r.prefilling = False
        r.prefill_pos = start + n
        self._pending_first.append({
            "handle": handle, "rows": [(0, r, slot, r.epoch)],
            "cold": r.cold_tokens, "t0": r.prefill_t0, "chunked": True})
        self._note_chunk_dispatch(n)

    def _note_chunk_dispatch(self, n: int) -> None:
        self.chunk_tokens_total += n
        self.chunk_dispatch_count += 1
        if self.m_chunk_tokens is not None:
            self.m_chunk_tokens.inc(n)

    def _retire_chunks(self, block: bool = False) -> None:
        """Pop completed chunk programs off the in-flight deque (oldest
        first; they complete in dispatch order) and record their spans.
        With ``block``, wait for the oldest — the prefill-only phase's
        pacing when the pipeline is full. ENGINE THREAD."""
        while self._chunk_inflight:
            entry = self._chunk_inflight[0]
            arr = entry["arr"]
            if not getattr(arr, "is_ready", lambda: True)():
                if not block:
                    break
                try:
                    arr.block_until_ready()
                except Exception:  # noqa: BLE001 — surfaces at final fetch
                    pass
                block = False  # only ever block on the oldest
            self._chunk_inflight.popleft()
            r = entry["r"]
            if self._recorder.enabled:
                self._recorder.add(
                    "prefill.chunk", r.ctx.trace_id, r.ctx.span_id,
                    entry["t0"], time.monotonic(),
                    attrs={"tokens": entry["tokens"],
                           "start": entry["start"]})
        if self.m_chunks_inflight is not None:
            self.m_chunks_inflight.set(len(self._chunk_inflight))

    def _abort_prefilling(self, r: _Request,
                          finish: FinishReason | None = None,
                          error: Exception | None = None) -> None:
        """Terminate a request mid-chunked-prefill (cancellation or a
        dispatch failure): the cold ledger is squared, the slot and pages
        free (deferred past in-flight device work), and the stream is
        closed with the finish reason or error. Chunk pages were never
        registered, so the prefix cache needs no scrub."""
        if r in self._prefilling:
            self._prefilling.remove(r)
        r.prefilling = False
        self._cold_inflight -= r.cold_tokens
        r.cold_tokens = 0
        if error is not None:
            r.push(RuntimeError(f"prefill failed: {error}"))
        else:
            r.push(LLMEngineOutput(
                token_ids=[],
                finish_reason=finish or FinishReason.CANCELLED).to_wire())
        self._finish_slot(r.slot, register=True)

    def _preempt_prefilling(self, r: _Request) -> None:
        """KV-pressure victim while still prefilling: drop the remaining
        chunk plan and requeue the whole request (recompute semantics —
        seeded draws are position-stable, so the retry's tokens are
        identical to an uninterrupted run)."""
        self._prefilling.remove(r)
        r.prefilling = False
        self._cold_inflight -= r.cold_tokens
        r.cold_tokens = 0
        self._requeue_slot(r.slot)

    def _prefill_chunked_token(self, r: _Request) -> int:
        """SYNCHRONOUS chunked prefill for the disagg extract path (runs
        as an engine-thread job between windows). Chunks are dispatched
        back-to-back with NO per-chunk host readback — only the final
        chunk's sampled token is fetched, one blocking round trip total.
        The serving path never comes here; it schedules chunks through
        _dispatch_prefill_chunks instead."""
        cfg = self.config
        prompt = r.tokens_all
        start = r.reuse_tokens  # cached prefix pinned by the plan
        max_chunk = min(cfg.max_prefill_tokens, cfg.prefill_buckets[-1])
        while start < len(prompt):
            n = min(max_chunk, len(prompt) - start)
            final = start + n >= len(prompt)
            seq = self._chunk_seq(r, start, n, final)
            if final:
                pen = self._penalties_of(r)
                rows = self._count_row_of(r)[None] if any(pen) else None
                return int(self.runner.prefill_batch(
                    [seq], count_rows=rows)[0])
            self.runner.prefill_chunk_async(seq)
            start += n
        raise AssertionError("chunked plan with no chunks")

    def _sampling_of(self, r: _Request) -> tuple[float, int, float]:
        s = r.req.sampling_options
        return (s.temperature or 0.0, s.top_k or 0, s.top_p or 1.0)

    def _set_seed_slot(self, r: _Request, slot: int) -> None:
        from dynamo_tpu.engine.runner import mask_seed
        seed = self._seed_of(r)
        self.seeded[slot] = seed is not None
        self.seeds[slot] = 0 if seed is None else mask_seed(seed)

    @staticmethod
    def _seed_of(r: _Request) -> int | None:
        return getattr(r.req.sampling_options, "seed", None)

    @staticmethod
    def _penalties_of(r: _Request) -> tuple[float, float]:
        s = r.req.sampling_options
        return (getattr(s, "frequency_penalty", None) or 0.0,
                getattr(s, "presence_penalty", None) or 0.0)

    def _count_row_of(self, r: _Request) -> np.ndarray:
        """uint8 [vocab] counts of this request's generated tokens so far
        (penalty state; saturates at 255). tokens_all is authoritative —
        every placement path appends the first token before calling."""
        row = np.zeros(self.runner.spec.vocab_size, np.int64)
        gen = r.tokens_all[len(r.req.token_ids):]
        if gen:
            np.add.at(row, np.asarray(gen, np.int64), 1)
        return np.minimum(row, 255).astype(np.uint8)

    def _place_in_slot_pending(self, r: _Request, slot: int) -> None:
        """Occupy a slot whose first token is still on device (scattered
        into tokens_dev by the prefill program): decode windows chain from
        it with no override; the host value is emitted when the async
        fetch resolves (_resolve_first)."""
        prompt_len = len(r.tokens_all)
        if not r.no_cache:
            for idx, h in enumerate(r.blocks.block_hashes):
                self.allocator.register(r.pages[idx], h)
        r.slot = slot
        r.epoch += 1
        r.last_token = None
        self.slot_req[slot] = r
        self.disp_positions[slot] = prompt_len
        self.disp_seq_lens[slot] = prompt_len + 1
        temp, tk, tp = self._sampling_of(r)
        self.temperature[slot] = temp
        self.top_k[slot] = tk
        self.top_p[slot] = tp
        self.freq_pen[slot], self.pres_pen[slot] = self._penalties_of(r)
        self.adapter_ids[slot] = r.adapter_slot
        self._set_seed_slot(r, slot)
        self.overrides.pop(slot, None)

    def _place_in_slot(self, r: _Request, slot: int, first_token: int,
                       lp_out: tuple[list, list] | None = None) -> None:
        prompt_len = len(r.tokens_all)
        # The prompt's complete blocks are now resident: register them for
        # prefix reuse + router events (multimodal requests skip the
        # cache: placeholder ids don't content-hash the media).
        if not r.no_cache:
            for idx, h in enumerate(r.blocks.block_hashes):
                self.allocator.register(r.pages[idx], h)
        r.generated += 1
        finish = self._check_finish(r, first_token)
        self._emit(r, [first_token], finish, lp_out)
        if finish is not None:
            self._pending_release.append((self._dispatch_serial, r.pages))
            r.pages = []
            self._release_adapter(r)
            return
        r.slot = slot
        r.epoch += 1
        r.last_token = first_token
        r.tokens_all.append(first_token)
        self.slot_req[slot] = r
        self.disp_positions[slot] = prompt_len
        self.disp_seq_lens[slot] = prompt_len + 1
        temp, tk, tp = self._sampling_of(r)
        self.temperature[slot] = temp
        self.top_k[slot] = tk
        self.top_p[slot] = tp
        fp, pp = self._penalties_of(r)
        self.freq_pen[slot], self.pres_pen[slot] = fp, pp
        self.adapter_ids[slot] = r.adapter_slot
        self._set_seed_slot(r, slot)
        if fp or pp:
            # tokens_all already includes first_token (appended above).
            self.runner.set_count_rows([slot], self._count_row_of(r)[None])
        self.overrides[slot] = first_token

    # -- decode windows -------------------------------------------------------
    # dtpu: hotpath -- decode-window dispatch: a sync device->host readback anywhere below stalls the software pipeline
    def _dispatch_window(self) -> _Window:
        cfg = self.config
        page = cfg.page_size
        # Window size is fixed: admission is never window-blocked in this
        # loop (_admit drains the waiting queue into free slots before
        # every dispatch, and dispatches are async), so an adaptive
        # shrink-while-waiting policy was tried and reverted — the only
        # states where requests persist in the queue are slot/KV
        # saturation, where short windows just multiply dispatch overhead
        # without admitting anyone (docs/PERF_NOTES.md, round-3 negative
        # results).
        M = self.decode_window
        b = cfg.max_num_seqs
        frozen: dict[int, tuple] = {}
        stalled: set[int] = set()
        satisfied: set[int] = set()
        deficits: dict[int, int] = {}
        needed_max = 1
        # Prefilling slots are invisible to the decode window: they have
        # no token chain yet, and their pages were fully allocated at
        # admission (chunk work never allocates mid-flight).
        live = [i for i, r in enumerate(self.slot_req)
                if r is not None and not r.prefilling]
        n_live = len(live)
        # Allocate pages oldest-request-first (requeued requests keep their
        # original enqueue time, so they age past new arrivals — no
        # starvation).
        order = sorted(live, key=lambda j: self.slot_req[j].enqueue_t)
        for i in order:
            r = self.slot_req[i]
            if int(self.disp_seq_lens[i]) >= r.len_cap:
                # Every token this request may emit is already produced
                # (the prefill's first token) or covered by an in-flight
                # window: more decode steps are dead compute. For a
                # max_tokens=1 burst — the disagg prefill-worker serving
                # pattern — this slot is only waiting on its first-token
                # readback, and a dispatched window would delay it.
                satisfied.add(i)
                continue
            last_pos = int(self.disp_positions[i]) + M - 1
            # Clamp to the model-length cap AND the request's own length
            # cap: the slot decodes up to its allocated capacity within the
            # window and freezes in-graph (the host emits LENGTH when
            # processing reaches the cap).
            needed = min(last_pos // page + 1, cfg.max_pages_per_seq,
                         (r.len_cap - 1) // page + 1)
            ok = True
            while len(r.pages) < needed:
                new = self.allocator.allocate(1)
                if new is None:
                    ok = False
                    break
                r.pages.extend(new)
            if not ok:
                pending = sum(len(p) for _, p in self._pending_release)
                if (n_live == 1 and not self._prefilling
                        and needed - len(r.pages)
                        > self.allocator.num_free + pending):
                    # Only live slot and the pool — even counting pages
                    # queued for release behind in-flight windows — is
                    # simply too small: fail it.
                    frozen[i] = (r, r.epoch, "oom")
                else:
                    deficits[i] = needed - len(r.pages)
                    stalled.add(i)
                continue
            needed_max = max(needed_max, len(r.pages))
        if deficits:
            # Preempt the YOUNGEST live slots (vLLM preempt-the-youngest
            # semantics) until the pages they will free (released after the
            # in-flight windows complete) — plus pages already queued for
            # release — cover what older slots still need. The
            # under-allocated older slots STALL this window: they keep all
            # state (pages, device token chain, pending override) and retry
            # next dispatch rather than being preempted themselves. The
            # very oldest slot is never a victim.
            freed = sum(len(p) for _, p in self._pending_release)
            want = sum(deficits.values())
            for j in reversed(order[1:]):
                if freed >= want:
                    break
                if j in frozen or j in satisfied:
                    # A satisfied slot's pages free the moment its
                    # first-token readback lands — preempting it would
                    # throw away a finished prefill for pages we get
                    # back on the next loop pass anyway.
                    continue
                r_j = self.slot_req[j]
                want -= deficits.pop(j, 0)  # a victim needs no pages
                stalled.discard(j)
                frozen[j] = (r_j, r_j.epoch, "requeue")
                freed += len(r_j.pages)
            if freed < want:
                # Decode victims alone can't cover the deficit: preempt
                # PREFILLING requests youngest-first (their chunk work is
                # recomputable, and prefix-cache hits make the re-prefill
                # cheap). Immediate — no in-flight window carries tokens
                # for a prefilling slot.
                for rp in sorted(self._prefilling,
                                 key=lambda x: x.enqueue_t, reverse=True):
                    if freed >= want:
                        break
                    freed += len(rp.pages)
                    self._preempt_prefilling(rp)
        active_rows = [i for i in live if i not in frozen
                       and i not in stalled and i not in satisfied]
        # A slot frozen at a PREVIOUS dispatch that this dispatch decided
        # to keep (allocation succeeded, or it merely stalls) is live again:
        # cancel the pending preemption records so processing the earlier
        # windows doesn't spuriously requeue or oom-fail it — this
        # dispatch's decision supersedes the previous ones.
        for w in self._inflight:
            for i in (*active_rows, *stalled, *satisfied):
                w.frozen.pop(i, None)
        self._dispatch_serial += 1
        if not active_rows:
            return _Window(toks=None, slots=[None] * b, frozen=frozen,
                           size=M, serial=self._dispatch_serial,
                           t0=time.monotonic())
        bucket = self.runner.bucket_pages_for(needed_max)
        packed = np.zeros((b, PK_PREFIX + bucket), np.int32)
        slots: list = [None] * b
        for i in active_rows:
            r = self.slot_req[i]
            # Consume the override only when the slot actually dispatches
            # (a frozen slot's first-token override must survive a retry).
            tok = self.overrides.pop(i, None)
            if tok is not None:
                packed[i, PK_OVERRIDE] = 1
                packed[i, PK_TOKEN] = tok
            start = int(self.disp_positions[i])
            cap = len(r.pages) * page
            packed[i, PK_POS] = start
            packed[i, PK_SEQLEN] = self.disp_seq_lens[i]
            packed[i, PK_TOPK] = self.top_k[i]
            packed[i, PK_TEMP] = self.temperature[i:i + 1].view(np.int32)[0]
            packed[i, PK_TOPP] = self.top_p[i:i + 1].view(np.int32)[0]
            packed[i, PK_CAP] = cap
            if r.req.sampling_options.logprobs is not None:
                packed[i, PK_LOGPROB] = 1
            packed[i, PK_FREQPEN] = self.freq_pen[i:i + 1].view(np.int32)[0]
            packed[i, PK_PRESPEN] = self.pres_pen[i:i + 1].view(np.int32)[0]
            packed[i, PK_SEED] = self.seeds[i]
            packed[i, PK_SEEDED] = int(self.seeded[i])
            packed[i, PK_ADAPTER] = self.adapter_ids[i]
            packed[i, PK_PREFIX:PK_PREFIX + len(r.pages)] = r.pages
            slots[i] = (r, r.epoch, start, cap)
            adv = min(M, max(0, cap - start))
            self.disp_positions[i] += adv
            self.disp_seq_lens[i] += adv
        self._flush_spills()
        # Brownout degradation hook: drop back to plain decode windows
        # while the engine-local pressure level is at/above the
        # configured threshold (0 in config disables the hook).
        use_spec = bool(self.config.spec_decode)
        if (use_spec and self.config.brownout_spec_disable_level
                and self.brownout_level
                >= self.config.brownout_spec_disable_level):
            use_spec = False
            self.spec_brownout_windows += 1
        if use_spec:
            outs = self.runner.decode_spec_window(
                packed, self.spec_m_outer, self.config.spec_k)
        else:
            outs = self.runner.decode_window(packed, M)
        for arr in outs:
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 — not all backends support it
                pass
        return _Window(toks=outs, slots=slots, frozen=frozen, size=M,
                       serial=self._dispatch_serial,
                       spec=use_spec,
                       t0=time.monotonic())

    def _process_window(self, w: _Window) -> None:
        if w.spec and w.toks is not None:
            self._process_spec_window(w)
            return
        page = self.config.page_size
        if w.toks is not None:
            toks = np.asarray(w.toks[0])
            want_lp = any(
                snap is not None
                and snap[0].req.sampling_options.logprobs is not None
                for snap in w.slots)
            lps = np.asarray(w.toks[1]) if want_lp else None
            top_vs = np.asarray(w.toks[2]) if want_lp else None
            top_is = np.asarray(w.toks[3]) if want_lp else None
            # Decode phase: dispatch -> readback complete (asarray blocks
            # on the device program).
            if self.phase is not None and w.t0:
                self.phase.decode.observe(time.monotonic() - w.t0)
        else:
            toks = None
        self._release_ready_pages()
        # Window processing walks host token chains; make sure every slot
        # this window touches has its first token resolved.
        if self._pending_first:
            need = {i for i, snap in enumerate(w.slots)
                    if snap is not None and snap[0].last_token is None}
            need |= {i for i, (fr, _, _) in w.frozen.items()
                     if fr.last_token is None}
            if need:
                self._force_resolve_first_for(need)
        for i, (fr, fepoch, reason) in w.frozen.items():
            r = self.slot_req[i]
            if r is not fr or r is None or r.epoch != fepoch:
                continue  # slot was re-assigned since dispatch
            if reason == "oom":
                r.push(RuntimeError(
                    "KV pool exhausted and no other request to preempt"))
                self._finish_slot(i, register=False)
            else:  # requeue (preemption)
                self._requeue_slot(i)
        if toks is None:
            return
        for i, snap in enumerate(w.slots):
            if snap is None:
                continue
            r, epoch, start, cap = snap
            if self.slot_req[i] is not r or r.epoch != epoch:
                continue  # slot was re-assigned since dispatch
            if r.ctx.is_killed:
                r.push(None)
                self._finish_slot(i, register=True)
                continue
            accepted: list[int] = []
            lp_out = ([], []) if r.req.sampling_options.logprobs is not None \
                else None
            finish = None
            inp = r.last_token
            for m in range(w.size):
                if start + m >= cap:
                    # The slot hit its page capacity (= max_model_len here:
                    # dispatch clamps allocation only at max_pages_per_seq)
                    # and froze in-graph.
                    finish = FinishReason.LENGTH
                    break
                token = int(toks[m, i])
                r.generated += 1
                new_block = r.blocks.append(inp)
                if new_block is not None and not r.no_cache:
                    # Register the just-completed page under its chained hash.
                    page_idx = (len(r.blocks.tokens) // page) - 1
                    self.allocator.register(r.pages[page_idx], new_block)
                accepted.append(token)
                if lp_out is not None:
                    k = r.req.sampling_options.logprobs or 0
                    lp_out[0].append(float(lps[m, i]))
                    lp_out[1].append(
                        [{"token_id": int(top_is[m, i, j]),
                          "logprob": float(top_vs[m, i, j])}
                         for j in range(k)])
                r.tokens_all.append(token)
                inp = token
                finish = self._check_finish(r, token)
                if finish is not None:
                    break
            r.last_token = inp
            if finish is None and r.ctx.is_stopped:
                finish = FinishReason.CANCELLED
            self.tokens_generated_total += len(accepted)
            if self._recorder.enabled and accepted:
                self._recorder.add(
                    "engine.decode", r.ctx.trace_id, r.ctx.span_id,
                    w.t0, time.monotonic(),
                    attrs={"tokens": len(accepted), "window": w.size})
            self._emit(r, accepted, finish, lp_out)
            if finish is not None:
                self._finish_slot(i, register=True)

    def _process_spec_window(self, w: _Window) -> None:
        """Host walk for a speculative window: per outer step the device
        emitted ``e`` tokens (1 + accepted drafts, 0 when frozen); the
        host appends them in order, applies stop conditions per token,
        and CORRECTS its dispatch-time position upper bound down to the
        actual advance (pipelined dispatches assumed the worst case)."""
        page = self.config.page_size
        outs = np.asarray(w.toks[0])     # [m, B, S]
        emits = np.asarray(w.toks[1])    # [m, B]
        ndrafts = np.asarray(w.toks[2])  # [m, B]
        if self.phase is not None and w.t0:
            self.phase.decode.observe(time.monotonic() - w.t0)
        self._release_ready_pages()
        if self._pending_first:
            need = {i for i, snap in enumerate(w.slots)
                    if snap is not None and snap[0].last_token is None}
            need |= {i for i, (fr, _, _) in w.frozen.items()
                     if fr.last_token is None}
            if need:
                self._force_resolve_first_for(need)
        for i, (fr, fepoch, reason) in w.frozen.items():
            r = self.slot_req[i]
            if r is not fr or r is None or r.epoch != fepoch:
                continue
            if reason == "oom":
                r.push(RuntimeError(
                    "KV pool exhausted and no other request to preempt"))
                self._finish_slot(i, register=False)
            else:
                self._requeue_slot(i)
        steps = outs.shape[0]
        for i, snap in enumerate(w.slots):
            if snap is None:
                continue
            r, epoch, start, cap = snap
            if self.slot_req[i] is not r or r.epoch != epoch:
                continue
            if r.ctx.is_killed:
                r.push(None)
                self._finish_slot(i, register=True)
                continue
            accepted: list[int] = []
            finish = None
            inp = r.last_token
            pos = start
            for m in range(steps):
                e = int(emits[m, i])
                self.spec_emit_hist[e] += 1
                if e == 0:
                    if pos >= cap:
                        finish = FinishReason.LENGTH
                    break
                nd = int(ndrafts[m, i])
                if nd:
                    self.spec_drafts += 1
                    self.spec_tokens += nd
                    self.spec_accepted += e - 1
                for j in range(e):
                    token = int(outs[m, i, j])
                    r.generated += 1
                    new_block = r.blocks.append(inp)
                    if new_block is not None and not r.no_cache:
                        page_idx = (len(r.blocks.tokens) // page) - 1
                        self.allocator.register(r.pages[page_idx],
                                                new_block)
                    accepted.append(token)
                    r.tokens_all.append(token)
                    inp = token
                    finish = self._check_finish(r, token)
                    if finish is not None:
                        break
                pos += e
                if finish is not None:
                    break
            r.last_token = inp
            if finish is None and r.ctx.is_stopped:
                finish = FinishReason.CANCELLED
            if finish is None:
                # Undo the dispatch-time worst-case advance assumption.
                # delta can be NEGATIVE when the device chain advanced
                # past the dispatch-time clamp (an earlier pipelined
                # window over-assumed near the page-capacity/len_cap
                # clamp): dropping that correction undercounts
                # disp_positions vs the device and can leave a
                # cap-frozen slot (e==0, host pos < cap) never emitting
                # LENGTH — apply it in both directions.
                assumed = min(w.size, max(0, cap - start))
                delta = assumed - (pos - start)
                if delta != 0:
                    self.disp_positions[i] -= delta
                    self.disp_seq_lens[i] -= delta
            self.tokens_generated_total += len(accepted)
            if self._recorder.enabled and accepted:
                self._recorder.add(
                    "engine.decode", r.ctx.trace_id, r.ctx.span_id,
                    w.t0, time.monotonic(),
                    attrs={"tokens": len(accepted), "window": w.size,
                           "spec": True})
            self._emit(r, accepted, finish, None)
            if finish is not None:
                self._finish_slot(i, register=True)

    def _check_finish(self, r: _Request, token: int) -> FinishReason | None:
        sc = r.req.stop_conditions
        if r.generated >= (sc.max_tokens or 2**30):
            return FinishReason.LENGTH
        if sc.min_tokens and r.generated < sc.min_tokens:
            return None
        if not sc.ignore_eos and token in (r.req.eos_token_ids or []):
            return FinishReason.EOS
        if token in (sc.stop_token_ids or []):
            return FinishReason.STOP
        return None

    def _emit(self, r: _Request, tokens: list[int],
              finish: FinishReason | None = None,
              lp_out: tuple[list, list] | None = None) -> None:
        out = LLMEngineOutput(token_ids=tokens, finish_reason=finish)
        if lp_out is not None:
            out.log_probs = lp_out[0]
            out.top_log_probs = lp_out[1]
        r.push(out.to_wire())

    def _finish_slot(self, slot: int, register: bool) -> None:
        r = self.slot_req[slot]
        self.slot_req[slot] = None
        self.disp_positions[slot] = 0
        self.disp_seq_lens[slot] = 0
        if 0 <= slot < len(self.adapter_ids):
            self.adapter_ids[slot] = 0
        self.overrides.pop(slot, None)
        if r is None:
            return
        self._release_adapter(r)
        r.slot = -1
        r.epoch += 1
        if not register:
            # Failure path: the pages' KV contents are suspect (partial
            # prefill / failed step) — drop their prefix-cache entries so no
            # future request reuses them.
            self.allocator.unregister(r.pages)
        # Defer the release until every in-flight window (which may still
        # scatter dummy K/V through the old page table) completes.
        self._pending_release.append((self._dispatch_serial, r.pages))
        r.pages = []

    def _requeue_slot(self, slot: int) -> None:
        """Preempt: free this slot's pages (prefix-cache entries survive so
        the re-prefill mostly hits) and requeue the request with its
        accumulated tokens."""
        r = self.slot_req[slot]
        self._finish_slot(slot, register=True)
        if r is None:
            return
        if r.ctx.is_killed or r.ctx.is_stopped:
            r.push(LLMEngineOutput(
                token_ids=[], finish_reason=FinishReason.CANCELLED).to_wire())
            return
        self.preempt_count += 1
        self.preempted_ids.append(r.ctx.id)
        r.wait_noted = False  # the second queue stint records its own wait
        log.warning("KV pool exhausted: preempting slot %d (request %s, "
                    "%d tokens so far) and requeueing", slot, r.ctx.id,
                    len(r.tokens_all))
        # Decision plane: preemption is an autonomous capacity decision
        # (engine thread; journal.emit is lock-only, no I/O). Cause: a
        # chaos injection when one is driving the pressure.
        journal.emit(EventKind.PREEMPT,
                     cause=(journal.recent_ref(EventKind.CHAOS_INJECT)
                            if chaos.ACTIVE else None),
                     trace_id=r.ctx.trace_id, request=r.ctx.id, slot=slot,
                     tokens=len(r.tokens_all),
                     free_pages=self.allocator.num_free)
        self._queue_put(r)

    # -- metrics + events -----------------------------------------------------
    def _note_flight(self, w: _Window) -> None:
        """One flight-recorder row per processed decode window (engine
        thread; the ring skips idle-stable windows itself) — plus the
        perf plane's roofline sample for the same window."""
        now = time.monotonic()
        tokens_total = self.tokens_generated_total
        # Roofline attribution (engine/perf.py): device window time +
        # tokens + dispatched rows -> EWMA step/tok_s/roofline gauges.
        # Plain stores; independent of the flight ring's frozen state.
        window_tokens = tokens_total - self._perf_tokens_last
        self._perf_tokens_last = tokens_total
        if w.t0 and w.toks is not None:
            self._perf.note_window(
                now - w.t0, window_tokens,
                sum(1 for snap in w.slots if snap is not None),
                w.size, self._step_floor_ms)
        fr = self._flight
        if not fr.enabled:
            return
        chunk_total = self.chunk_tokens_total
        accepted = fr.record(
            now, now - w.t0 if w.t0 else 0.0,
            sum(1 for r in self.slot_req if r is not None),
            self.num_waiting, self.allocator.num_free,
            chunk_total - self._flight_chunk_last,
            len(self._chunk_inflight), self.preempt_count,
            self.brownout_level, self._flight_stall_last,
            self.step_count, tokens_total - self._flight_tokens_last)
        if accepted:
            # A frozen ring (bundle capture in flight) rejects the row:
            # keep accumulating so the stall/chunk/token deltas land in
            # the first post-thaw record instead of vanishing.
            self._flight_chunk_last = chunk_total
            self._flight_stall_last = 0.0
            self._flight_tokens_last = tokens_total

    def _publish(self) -> None:
        if self.kv_metrics is not None:
            # /metrics export is loop-independent (in-process pipelines
            # without a coordinator still get dynamo_tpu_kv_* series).
            self.kv_metrics.update(self)
        if self.perf_metrics is not None:
            self.perf_metrics.update(self)
        if self.adapter_metrics is not None:
            self.adapter_metrics.update(self.adapters)
        loop = self._publish_loop
        if loop is None or loop.is_closed():
            self.allocator.drain_events()
            return
        stored, removed = self.allocator.drain_events()
        # Inventory digest: built on the engine thread only when the
        # publisher's cadence is due (a k-min sketch over the registered
        # hashes — bounded work, every ~2s).
        digest = None
        if self.inventory_publisher is not None \
                and self.inventory_publisher.due(time.monotonic()):
            digest = self.inventory_digest()
        active = sum(1 for r in self.slot_req if r is not None)
        hit = (self.prefix_hit_blocks / self.prefix_lookup_blocks
               if self.prefix_lookup_blocks else 0.0)
        metrics = ForwardPassMetrics(
            worker_stats=WorkerStats(
                request_active_slots=active,
                request_total_slots=self.config.max_num_seqs,
                num_requests_waiting=self.num_waiting),
            kv_stats=KvStats(
                kv_active_blocks=self.allocator.num_active,
                kv_total_blocks=self.allocator.num_pages,
                gpu_cache_usage_perc=(self.allocator.num_active
                                      / self.allocator.num_pages),
                gpu_prefix_cache_hit_rate=hit),
            spec_decode_stats=(SpecDecodeStats(
                num_spec_tokens=self.spec_tokens,
                num_drafts=self.spec_drafts,
                num_accepted_tokens=self.spec_accepted)
                if self.config.spec_decode else None))

        async def do_publish():
            try:
                if self.kv_publisher is not None:
                    if stored:
                        await self.kv_publisher.stored(stored)
                    if removed:
                        await self.kv_publisher.removed(removed)
                if self.metrics_publisher is not None:
                    force = active == 0 and self.num_waiting == 0
                    await self.metrics_publisher.publish(metrics, force=force)
                if digest is not None:
                    await self.inventory_publisher.publish(digest)
            except Exception:  # noqa: BLE001
                log.exception("publish failed")

        if (self.kv_publisher is not None or self.metrics_publisher is not None
                or digest is not None):
            asyncio.run_coroutine_threadsafe(do_publish(), loop)
