"""HF safetensors -> dynamo_tpu parameter loading.

Maps HF Llama/Qwen2 checkpoint names onto the stacked scan-over-layers pytree
(model.py param_shapes). Loads on host CPU; the runner shards onto the mesh.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("weights")


def load_hf_weights(spec: ModelSpec, model_dir: str):
    """Load *.safetensors from ``model_dir`` into our param pytree (numpy,
    bf16 via ml_dtypes)."""
    import ml_dtypes
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")
    tensors: dict[str, np.ndarray] = {}
    wanted_prefixes = ("model.", "lm_head.")
    for path in files:
        with safe_open(path, framework="numpy") as fh:
            for name in fh.keys():
                if name.startswith(wanted_prefixes):
                    tensors[name] = fh.get_tensor(name)

    bf16 = ml_dtypes.bfloat16

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(f"missing tensor {name}")
        return tensors[name].astype(bf16)

    L = spec.num_layers
    names = ["input_norm", "post_attn_norm", "wq", "wk", "wv", "wo"]
    if spec.num_experts:
        names += ["moe_gate", "moe_w_gate", "moe_w_up", "moe_w_down"]
    else:
        names += ["w_gate", "w_up", "w_down"]
    layers: dict[str, list] = {k: [] for k in names}
    if spec.qkv_bias:
        for k in ("bq", "bk", "bv"):
            layers[k] = []
    for i in range(L):
        p = f"model.layers.{i}."
        layers["input_norm"].append(get(p + "input_layernorm.weight"))
        layers["post_attn_norm"].append(
            get(p + "post_attention_layernorm.weight"))
        # HF linear weights are [out, in]; ours are [in, out].
        layers["wq"].append(get(p + "self_attn.q_proj.weight").T)
        layers["wk"].append(get(p + "self_attn.k_proj.weight").T)
        layers["wv"].append(get(p + "self_attn.v_proj.weight").T)
        layers["wo"].append(get(p + "self_attn.o_proj.weight").T)
        if spec.num_experts:
            # Mixtral: block_sparse_moe.gate + experts.N.{w1,w3,w2} =
            # (gate_proj, up_proj, down_proj).
            m = p + "block_sparse_moe."
            layers["moe_gate"].append(get(m + "gate.weight").T)
            layers["moe_w_gate"].append(np.stack(
                [get(f"{m}experts.{e}.w1.weight").T
                 for e in range(spec.num_experts)]))
            layers["moe_w_up"].append(np.stack(
                [get(f"{m}experts.{e}.w3.weight").T
                 for e in range(spec.num_experts)]))
            layers["moe_w_down"].append(np.stack(
                [get(f"{m}experts.{e}.w2.weight").T
                 for e in range(spec.num_experts)]))
        else:
            layers["w_gate"].append(get(p + "mlp.gate_proj.weight").T)
            layers["w_up"].append(get(p + "mlp.up_proj.weight").T)
            layers["w_down"].append(get(p + "mlp.down_proj.weight").T)
        if spec.qkv_bias:
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
    params = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
    }
    if not spec.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T
    log.info("loaded %d tensors from %s", len(tensors), model_dir)
    return params
