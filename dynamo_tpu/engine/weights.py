"""HF safetensors -> dynamo_tpu parameter loading.

Maps HF Llama/Qwen2 checkpoint names onto the stacked scan-over-layers pytree
(model.py param_shapes). Loads on host CPU; the runner shards onto the mesh.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("weights")


def load_hf_weights(spec: ModelSpec, model_dir: str):
    """Load *.safetensors from ``model_dir`` into our param pytree (numpy,
    bf16 via ml_dtypes)."""
    import ml_dtypes
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")
    tensors: dict[str, np.ndarray] = {}
    wanted_prefixes = ("model.", "lm_head.")
    for path in files:
        with safe_open(path, framework="numpy") as fh:
            for name in fh.keys():
                if name.startswith(wanted_prefixes):
                    tensors[name] = fh.get_tensor(name)

    bf16 = ml_dtypes.bfloat16

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(f"missing tensor {name}")
        return tensors[name].astype(bf16)

    L = spec.num_layers
    names = ["input_norm", "post_attn_norm", "wq", "wk", "wv", "wo"]
    if spec.num_experts:
        names += ["moe_gate", "moe_w_gate", "moe_w_up", "moe_w_down"]
    else:
        names += ["w_gate", "w_up", "w_down"]
    layers: dict[str, list] = {k: [] for k in names}
    if spec.qkv_bias:
        for k in ("bq", "bk", "bv"):
            layers[k] = []
    for i in range(L):
        p = f"model.layers.{i}."
        layers["input_norm"].append(get(p + "input_layernorm.weight"))
        layers["post_attn_norm"].append(
            get(p + "post_attention_layernorm.weight"))
        # HF linear weights are [out, in]; ours are [in, out].
        layers["wq"].append(get(p + "self_attn.q_proj.weight").T)
        layers["wk"].append(get(p + "self_attn.k_proj.weight").T)
        layers["wv"].append(get(p + "self_attn.v_proj.weight").T)
        layers["wo"].append(get(p + "self_attn.o_proj.weight").T)
        if spec.num_experts:
            # Mixtral: block_sparse_moe.gate + experts.N.{w1,w3,w2} =
            # (gate_proj, up_proj, down_proj).
            m = p + "block_sparse_moe."
            layers["moe_gate"].append(get(m + "gate.weight").T)
            layers["moe_w_gate"].append(np.stack(
                [get(f"{m}experts.{e}.w1.weight").T
                 for e in range(spec.num_experts)]))
            layers["moe_w_up"].append(np.stack(
                [get(f"{m}experts.{e}.w3.weight").T
                 for e in range(spec.num_experts)]))
            layers["moe_w_down"].append(np.stack(
                [get(f"{m}experts.{e}.w2.weight").T
                 for e in range(spec.num_experts)]))
        else:
            layers["w_gate"].append(get(p + "mlp.gate_proj.weight").T)
            layers["w_up"].append(get(p + "mlp.up_proj.weight").T)
            layers["w_down"].append(get(p + "mlp.down_proj.weight").T)
        if spec.qkv_bias:
            layers["bq"].append(get(p + "self_attn.q_proj.bias"))
            layers["bk"].append(get(p + "self_attn.k_proj.bias"))
            layers["bv"].append(get(p + "self_attn.v_proj.bias"))
    params = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
    }
    if not spec.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T
    log.info("loaded %d tensors from %s", len(tensors), model_dir)
    return params


def load_lora_weights(spec: ModelSpec, adapter_dir: str, max_rank: int):
    """Load a HF PEFT LoRA checkpoint into stacked per-projection pairs.

    Reads ``adapter_config.json`` (r, lora_alpha, target_modules) and
    ``adapter_model.safetensors`` from ``adapter_dir`` and returns
    ``{key: (A [L, d_in, max_rank], B [L, max_rank, d_out])}`` numpy
    bf16 pytrees over the projections the checkpoint targets (subset of
    wq/wk/wv/wo + dense MLP). PEFT stores ``lora_A.weight`` as [r, in]
    and ``lora_B.weight`` as [out, r]; ours are the transposes, with the
    ``lora_alpha / r`` scale folded into B so serving pays no extra
    multiply. Ranks below ``max_rank`` zero-pad — padded columns
    contribute exact zeros, so heterogeneous-rank adapters share one
    static stack shape. Layers or projections the checkpoint does not
    cover stay zero (no delta).
    """
    import ml_dtypes
    from safetensors import safe_open

    cfg_path = os.path.join(adapter_dir, "adapter_config.json")
    # dtpu: ignore[blocking-call-in-async] -- adapter-load startup/hot-load I/O, engine-thread or CLI, never the serving loop
    with open(cfg_path) as fh:
        cfg = json.load(fh)
    rank = int(cfg.get("r", 8))
    alpha = float(cfg.get("lora_alpha", rank))
    if rank > max_rank:
        raise ValueError(
            f"adapter rank {rank} exceeds lora_max_rank {max_rank} "
            f"({adapter_dir}); raise --max-lora-rank or re-train smaller")
    scale = alpha / max(1, rank)

    files = sorted(glob.glob(os.path.join(adapter_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors under {adapter_dir}")
    tensors: dict[str, np.ndarray] = {}
    for path in files:
        with safe_open(path, framework="numpy") as fh:
            for name in fh.keys():
                tensors[name] = fh.get_tensor(name)

    # HF module suffix -> our stacked projection key.
    proj_of = {"q_proj": "wq", "k_proj": "wk", "v_proj": "wv",
               "o_proj": "wo", "gate_proj": "w_gate", "up_proj": "w_up",
               "down_proj": "w_down"}
    if spec.num_experts:
        for k in ("gate_proj", "up_proj", "down_proj"):
            proj_of.pop(k)
    L = spec.num_layers
    bf16 = ml_dtypes.bfloat16
    found: dict[str, dict[int, tuple[np.ndarray, np.ndarray]]] = {}
    for name, arr in tensors.items():
        # base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight
        parts = name.split(".")
        if "layers" not in parts or "weight" != parts[-1]:
            continue
        li = int(parts[parts.index("layers") + 1])
        module = parts[-3]
        kind = parts[-2]  # lora_A | lora_B
        key = proj_of.get(module)
        if key is None or kind not in ("lora_A", "lora_B") or li >= L:
            continue
        a, b = found.setdefault(key, {}).get(li, (None, None))
        if kind == "lora_A":
            a = arr
        else:
            b = arr
        found[key][li] = (a, b)
    if not found:
        raise ValueError(
            f"{adapter_dir}: no LoRA tensors matched the target "
            f"projections {sorted(proj_of.values())}")

    out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for key, per_layer in found.items():
        # d_in/d_out from the checkpoint itself (validated against the
        # model by the AdapterStore at registration).
        li0 = next(iter(per_layer))
        a0, b0 = per_layer[li0]
        d_in = a0.shape[1]
        d_out = b0.shape[0]
        A = np.zeros((L, d_in, max_rank), bf16)
        B = np.zeros((L, max_rank, d_out), bf16)
        for li, (a, b) in per_layer.items():
            if a is None or b is None:
                raise ValueError(
                    f"{adapter_dir}: layer {li} {key} has only one of "
                    f"lora_A/lora_B")
            r = a.shape[0]
            A[li, :, :r] = a.astype(np.float32).T.astype(bf16)
            B[li, :r, :] = (b.astype(np.float32).T * scale).astype(bf16)
        out[key] = (A, B)
    log.info("loaded LoRA adapter from %s: rank %d (padded to %d), "
             "targets %s", adapter_dir, rank, max_rank, sorted(out))
    return out
