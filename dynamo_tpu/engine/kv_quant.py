"""int8 KV-cache quantization (``--quant-kv int8``).

Decode at long context is bound by attention bandwidth — every step reads
the sequence's whole cache-resident history from HBM — and the pool's
page count caps concurrent sequences per chip. ``--quant int8`` halved
the weight side of the bandwidth budget (engine/quant.py); this module
halves the KV side, KIVI-style: paged K/V blocks store int8 with one
float32 absmax scale PER TOKEN PER HEAD (per-page scale rows — the
scales array is indexed [L, Nkv, page_id, page_off] right beside the
pages), dequantized in the same fused expression that reads them:

- the Pallas decode kernel (engine/attention.py) DMAs int8 pages plus the
  small scale rows HBM->VMEM and dequantizes in-register — no bf16 copy
  of the history is ever materialized;
- the XLA gather paths multiply the gathered pages by the gathered
  scales, which XLA fuses into the gather consumer;
- quantization is fused into every KV write: the prefill page scatter
  and the per-window decode commit scatter quantize in-graph.

Per-token scales (not one scale per page) are what make the decode
commit correct: a page fills across multiple windows, and a
whole-page absmax could not be recomputed without reading the page
back. Cost: 4 bytes per (layer, kv-head, token) next to head_dim int8
bytes — ~1.9x pool compression at head_dim 64–128, so ~2x resident
slots per HBM GB (PageAllocator pages at equal budget).

Wire/tier parcel format: host-side parcels pack data + scales into one
uint8 array ``[..., page, head_dim + 4]`` (the last 4 "lanes" are the
f32 scale bytes), so every existing parcel path — host/disk tiers,
KV-plane tickets, G4 block fetches, np.stack/slicing — carries the
compressed form unchanged, at ~half the bf16 bytes. ``pack_parcel`` /
``unpack_parcel`` are the codec; a parcel's dtype says which form it is
(uint8 = packed int8+scales, bfloat16 = raw).

QuantKV is a NamedTuple, hence a pytree: jit signatures, donation and
sharding trees compose without special cases, exactly like QTensor.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

# f32 scale bytes appended per (layer, head, token) row in packed parcels.
KV_SCALE_BYTES = 4


class QuantKV(NamedTuple):
    """int8 paged KV pool + per-token-per-head scales.

    data  int8    [L, Nkv, P, page, D]
    scale float32 [L, Nkv, P, page]
    """
    data: Any
    scale: Any

    @property
    def shape(self):
        # The logical (value) shape: call sites size buffers and read
        # page/head dims off ``cache.shape`` exactly as for a bf16 pool.
        return self.data.shape

    @property
    def dtype(self):
        # The VALUE dtype: buffers holding unquantized K/V (window
        # buffers, the self column) allocate with ``cache.dtype``.
        import jax.numpy as jnp

        return jnp.bfloat16


def is_quantized(cache) -> bool:
    return isinstance(cache, QuantKV)


# ---------------------------------------------------------------------------
# Traceable quantize/dequantize (inside jitted programs)
# ---------------------------------------------------------------------------

def kv_quantize(x):
    """Symmetric per-token absmax int8 over the last (head_dim) axis.
    x [..., D] -> (q int8 [..., D], s float32 [...]). All-zero rows get
    s=1 so dequant stays exact (matches quantize_weight's convention)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def kv_dequantize(q, s):
    """(int8 [..., D], f32 [...]) -> bf16 [..., D]."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32) * s[..., None]).astype(jnp.bfloat16)


def gather_pages(cache, idx_l, page_table):
    """The layer-folded history gather ``cache[idx_l, :, page_table]``
    ([B, maxP, Nkv, page, D] bf16), dequantizing int8 pools in the same
    expression (XLA fuses the scale multiply into the gather consumer)."""
    if isinstance(cache, QuantKV):
        return kv_dequantize(cache.data[idx_l, :, page_table],
                             cache.scale[idx_l, :, page_table])
    return cache[idx_l, :, page_table]


def gather_pages_folded(cache, layer, page_table):
    """History gather with the LAYER AND HEAD axes both folded into one
    gather: ``[Nkv, B, maxP*page, D]`` — exactly the attention dot's
    K/V operand layout. gather_pages' natural output puts the advanced
    (batch, page) indices first, so every attention consumer paid a
    ``transpose(2,0,1,3,4)`` relayout of the WHOLE gathered history —
    a full extra HBM round-trip per step per cache. A gather is already
    arbitrary data movement, so asking it for the permuted layout
    directly is free; the reshape that follows is contiguous (no copy).
    The layer index stays an ADVANCED index on purpose — a basic
    ``cache[layer]`` scalar index is a dynamic-slice copy of cache/L
    (the 50 ms-per-step failure mode gather_pages exists to avoid)."""
    import jax.numpy as jnp

    b, maxp = page_table.shape
    data = cache.data if isinstance(cache, QuantKV) else cache
    nkv, page, d = data.shape[1], data.shape[3], data.shape[4]
    idx_l = jnp.broadcast_to(layer, (nkv, b, maxp))
    idx_n = jnp.arange(nkv)[:, None, None]
    pt = jnp.broadcast_to(page_table[None], (nkv, b, maxp))
    if isinstance(cache, QuantKV):
        out = kv_dequantize(cache.data[idx_l, idx_n, pt],
                            cache.scale[idx_l, idx_n, pt])
    else:
        out = cache[idx_l, idx_n, pt]
    return out.reshape(nkv, b, maxp * page, d)


def scatter_pages(cache, blocks, flat_pages):
    """Whole-page commit ``cache.at[:, :, flat_pages].set(blocks)`` with
    quantization fused in for int8 pools. blocks [L, Nkv, n, page, D]."""
    if isinstance(cache, QuantKV):
        q, s = kv_quantize(blocks)
        return QuantKV(cache.data.at[:, :, flat_pages].set(q),
                       cache.scale.at[:, :, flat_pages].set(s))
    return cache.at[:, :, flat_pages].set(blocks)


def scatter_tokens(cache, vals, dest, off):
    """Per-token commit ``cache.at[:, :, dest, off].set(vals)`` (the
    decode-window scatter) with quantization fused in. vals [L, Nkv, ...,
    D]; dest/off broadcastable index arrays."""
    if isinstance(cache, QuantKV):
        q, s = kv_quantize(vals)
        return QuantKV(cache.data.at[:, :, dest, off].set(q),
                       cache.scale.at[:, :, dest, off].set(s))
    return cache.at[:, :, dest, off].set(vals)


# ---------------------------------------------------------------------------
# Host-side (numpy) twins + the packed parcel codec
# ---------------------------------------------------------------------------

def quantize_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host twin of kv_quantize (f32 math, round-half-even like
    jnp.round, so host- and device-quantized blocks agree bit-for-bit)."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=-1)
    s = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(xf / s[..., None]), -127, 127).astype(np.int8)
    return q, s


def dequantize_np(q: np.ndarray, s: np.ndarray) -> np.ndarray:
    import ml_dtypes

    return (q.astype(np.float32) * np.asarray(s, np.float32)[..., None]) \
        .astype(ml_dtypes.bfloat16)


def pack_parcel(data: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """(int8 [..., page, D], f32 [..., page]) -> uint8 [..., page, D+4].
    One contiguous array so every tier/wire path (np.stack, page-axis
    slicing, msgpack raw bytes) carries the compressed form unchanged."""
    d = data.shape[-1]
    out = np.empty((*data.shape[:-1], d + KV_SCALE_BYTES), np.uint8)
    out[..., :d] = data.view(np.uint8)
    out[..., d:] = np.ascontiguousarray(
        np.asarray(scale, np.float32)).view(np.uint8) \
        .reshape(*scale.shape, KV_SCALE_BYTES)
    return out


def unpack_parcel(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint8 [..., page, D+4] -> (int8 [..., page, D], f32 [..., page])."""
    d = packed.shape[-1] - KV_SCALE_BYTES
    data = np.ascontiguousarray(packed[..., :d]).view(np.int8)
    scale = np.ascontiguousarray(packed[..., d:]).view(np.float32)[..., 0]
    return data, scale


def is_packed_parcel(arr: np.ndarray) -> bool:
    """Parcel form by dtype: uint8 = packed int8+scales, else raw bf16."""
    return arr.dtype == np.uint8


def parcel_to_bf16(arr: np.ndarray) -> np.ndarray:
    return dequantize_np(*unpack_parcel(arr)) if is_packed_parcel(arr) \
        else arr


def parcel_to_packed(arr: np.ndarray) -> np.ndarray:
    return arr if is_packed_parcel(arr) else pack_parcel(*quantize_np(arr))
