"""dynamo_tpu_kv_* exporter: engine KV state -> Prometheus.

The engine's KV structures (PageAllocator, Host/Disk tiers, the KV data
plane, the G4 remote source) keep plain-int telemetry so the engine
thread never takes a Prometheus lock per operation. This updater turns
those into registered series on a throttle: gauges are set directly,
monotonic ints become counter *deltas* so restarts of the structures
(clear_kv_blocks) can't make counters go backwards. Every series here is
documented in docs/OBSERVABILITY.md "KV & capacity" (tier-1 docs-drift
guard, tests/test_slo.py).
"""

from __future__ import annotations

import time

_LAT_BUCKETS = [.001, .005, .01, .025, .05, .1, .25, .5, 1, 2.5]


class KvMetricsUpdater:
    def __init__(self, registry, min_interval_s: float = 0.5):
        self.min_interval_s = min_interval_s
        self._next = 0.0
        self._last: dict[tuple, float] = {}
        self.g_pages = registry.gauge(
            "kv_pages", "HBM KV pages by lifecycle state", ["state"])
        self.g_occupancy = registry.gauge(
            "kv_occupancy", "Fraction of HBM KV pages held by live "
            "sequences")
        self.g_cached_blocks = registry.gauge(
            "kv_cached_blocks", "Registered (reusable) KV blocks in HBM")
        self.g_pool_bytes = registry.gauge(
            "kv_pool_bytes", "Device KV pool bytes at the ACTUAL pool "
            "dtype (int8 pages + scales under --quant-kv, bf16 "
            "otherwise) — halves when the pool quantizes, while kv_pages "
            "doubles at equal HBM budget")
        self.c_reuse = registry.counter(
            "kv_reuse_blocks_total", "Prefix blocks reused instead of "
            "recomputed, by serving tier", ["tier"])
        self.c_reuse_lookup = registry.counter(
            "kv_reuse_lookup_blocks_total", "Prefix blocks probed against "
            "the HBM cache")
        self.c_evicted = registry.counter(
            "kv_evicted_blocks_total", "Inactive HBM blocks LRU-evicted "
            "under allocation pressure")
        self.c_cleared = registry.counter(
            "kv_cleared_blocks_total", "HBM blocks dropped by "
            "clear_inactive (admin clear_kv_blocks)")
        self.g_tier_blocks = registry.gauge(
            "kv_tier_blocks", "Resident KV blocks per offload tier",
            ["tier"])
        self.g_tier_bytes = registry.gauge(
            "kv_tier_bytes", "Approximate bytes per offload tier", ["tier"])
        self.c_tier_hits = registry.counter(
            "kv_tier_hits_total", "Block gets served by an offload tier",
            ["tier"])
        self.c_tier_misses = registry.counter(
            "kv_tier_misses_total", "Block gets that missed an offload "
            "tier", ["tier"])
        self.c_tier_spills = registry.counter(
            "kv_tier_spills_total", "Blocks offloaded into a tier (g2: "
            "HBM evictions; g3: g2 capacity demotions)", ["tier"])
        self.c_plane_pulls = registry.counter(
            "kv_plane_pulls_total", "KV-plane parcel pulls completed by "
            "this worker")
        self.c_plane_pull_seconds = registry.counter(
            "kv_plane_pull_seconds_total", "Wall-clock seconds spent in "
            "KV-plane pulls (rate / pulls rate = mean latency)")
        self.c_plane_bytes = registry.counter(
            "kv_plane_bytes_total", "KV-plane bulk bytes by direction",
            ["direction"])
        self.c_plane_blocks_served = registry.counter(
            "kv_plane_blocks_served_total", "G4 blocks served to peers "
            "from this worker's host tiers")
        # KV federation (engine/kvbm.py; docs/OBSERVABILITY.md "KV
        # federation"): the placement-policy counters, distinct from the
        # mechanism counters above — watermark demotions are proactive
        # (vs kv_evicted_blocks_total's allocation-pressure evictions),
        # promotions count blocks moved UP the ladder into HBM.
        self.c_fed_demotions = registry.counter(
            "kv_federation_demotions_total", "Blocks proactively demoted "
            "by the KVBM watermark sweep (HBM free-list hysteresis)")
        self.c_fed_promotions = registry.counter(
            "kv_federation_promotions_total", "Tier blocks promoted into "
            "HBM pages (host/disk/peer onboards)")
        self.c_fed_recompute = registry.counter(
            "kv_federation_recompute_fallbacks_total", "Tier walks that "
            "ran dry before the request's full prefix (remainder "
            "recomputed — the always-safe fallback)")
        self.c_fed_peer_failures = registry.counter(
            "kv_federation_peer_pull_failures_total", "Peer block pulls "
            "that failed (breaker-open peers, timeouts, transport "
            "errors); the request recomputed instead")
        self.g_fed_pinned = registry.gauge(
            "kv_federation_pinned_blocks", "Blocks pinned against "
            "watermark demotion (KVBM pin set)")
        for tier in ("hbm", "host", "peer"):
            self.c_reuse.ensure(tier=tier)
        for bound in (self.g_occupancy, self.g_cached_blocks,
                      self.g_pool_bytes,
                      self.c_reuse_lookup, self.c_evicted, self.c_cleared,
                      self.c_plane_pulls, self.c_plane_pull_seconds,
                      self.c_plane_blocks_served, self.c_fed_demotions,
                      self.c_fed_promotions, self.c_fed_recompute,
                      self.c_fed_peer_failures, self.g_fed_pinned):
            bound.ensure()

    def _delta(self, bound, key: tuple, current: float, **labels) -> None:
        prev = self._last.get(key, 0.0)
        if current > prev:
            bound.inc(current - prev, **labels)
        self._last[key] = current

    def update(self, engine, force: bool = False) -> None:
        """Engine-thread safe (Prometheus child ops take a lock, but only
        every ``min_interval_s``). ``engine`` duck-types TPUEngine: needs
        .allocator, .host_cache, .onboard_blocks, .g4_blocks, and
        optionally .plane / .remote_source set by the worker main."""
        now = time.monotonic()
        if not force and now < self._next:
            return
        self._next = now + self.min_interval_s
        alloc = engine.allocator.stats()
        self.g_pages.set(alloc["pages_free"], state="free")
        self.g_pages.set(alloc["pages_active"], state="active")
        self.g_pages.set(alloc["pages_inactive"], state="inactive")
        self.g_occupancy.set(alloc["occupancy"])
        self.g_cached_blocks.set(alloc["cached_blocks"])
        runner = getattr(engine, "runner", None)
        if runner is not None:
            self.g_pool_bytes.set(getattr(runner, "kv_pool_bytes", 0))
        self._delta(self.c_reuse_lookup, ("lookup",),
                    alloc["reuse_lookup_blocks"])
        self._delta(self.c_evicted, ("evicted",), alloc["evicted_blocks"])
        self._delta(self.c_cleared, ("cleared",), alloc["cleared_blocks"])
        # Reuse attribution by tier: HBM hits from the allocator, host
        # (G2/G3) vs peer (G4) from the engine's onboard counters.
        g4 = getattr(engine, "g4_blocks", 0)
        onboard = getattr(engine, "onboard_blocks", 0)
        self._delta(self.c_reuse, ("reuse", "hbm"),
                    alloc["reuse_hit_blocks"], tier="hbm")
        self._delta(self.c_reuse, ("reuse", "host"), onboard - g4,
                    tier="host")
        self._delta(self.c_reuse, ("reuse", "peer"), g4, tier="peer")
        host = getattr(engine, "host_cache", None)
        if host is not None:
            tiers = host.stats()
            for tier in ("g2", "g3"):
                if f"{tier}_blocks" not in tiers:
                    continue
                self.g_tier_blocks.set(tiers[f"{tier}_blocks"], tier=tier)
                self.g_tier_bytes.set(tiers.get(f"{tier}_bytes", 0),
                                      tier=tier)
                self._delta(self.c_tier_hits, ("hits", tier),
                            tiers[f"{tier}_hits"], tier=tier)
                self._delta(self.c_tier_misses, ("misses", tier),
                            tiers[f"{tier}_misses"], tier=tier)
            self._delta(self.c_tier_spills, ("spills", "g2"),
                        tiers.get("g2_spills_in", 0), tier="g2")
            self._delta(self.c_tier_spills, ("spills", "g3"),
                        tiers.get("g2_demotions", 0), tier="g3")
        kvbm = getattr(engine, "kvbm", None)
        if kvbm is not None:
            self._delta(self.c_fed_demotions, ("fed_demote",),
                        kvbm.watermark_demotions)
            self._delta(self.c_fed_promotions, ("fed_promote",),
                        kvbm.promotions)
            self._delta(self.c_fed_recompute, ("fed_recompute",),
                        kvbm.recompute_fallbacks)
            self._delta(self.c_fed_peer_failures, ("fed_peer_fail",),
                        kvbm.peer_pull_failures)
            self.g_fed_pinned.set(len(kvbm.pinned))
        remote = getattr(engine, "remote_source", None)
        if remote is not None:
            self._delta(self.c_fed_peer_failures, ("peer_fetch_fail",),
                        remote.fetch_failures)
            client = remote.client
            self._delta(self.c_plane_pulls, ("pulls",), client.transfers)
            self._delta(self.c_plane_pull_seconds, ("pull_s",),
                        client.pull_seconds_total)
            self._delta(self.c_plane_bytes, ("bytes", "in"),
                        client.bytes_in, direction="in")
        plane = getattr(engine, "plane", None)
        if plane is not None:
            self._delta(self.c_plane_bytes, ("bytes", "out"),
                        plane.bytes_out, direction="out")
            self._delta(self.c_plane_blocks_served, ("served",),
                        plane.blocks_served)


class AdapterMetricsUpdater:
    """dynamo_tpu_adapter_* exporter (engine/lora.py AdapterStore ->
    Prometheus, same discipline as KvMetricsUpdater: the store keeps
    plain ints, gauges set directly, monotonic ints become counter
    deltas on a throttle). Documented in docs/OBSERVABILITY.md
    "Adapters" (whole-family docs-drift guard, tests/test_slo.py)."""

    def __init__(self, registry, min_interval_s: float = 0.5):
        self.min_interval_s = min_interval_s
        self._next = 0.0
        self._last: dict[tuple, float] = {}
        self.g_resident = registry.gauge(
            "adapter_resident", "LoRA adapters currently resident in "
            "device slots (hot; excludes host-registered-only adapters)")
        self.c_loads = registry.counter(
            "adapter_loads_total", "Adapter device uploads (cold first "
            "loads + hot-reloads after eviction)")
        self.c_evictions = registry.counter(
            "adapter_evictions_total", "Adapter slot evictions (LRU "
            "pressure + explicit admin evicts)")
        self.c_miss = registry.counter(
            "adapter_miss_total", "Requests that arrived while their "
            "adapter was NOT resident (each forces a hot-load — a high "
            "rate is an adapter-miss storm: raise --max-adapters or pin)")
        self.c_requests = registry.counter(
            "adapter_requests_total", "Requests resolved per adapter "
            "name", ["adapter"])
        for bound in (self.g_resident, self.c_loads, self.c_evictions,
                      self.c_miss):
            bound.ensure()

    def _delta(self, bound, key: tuple, current: float, **labels) -> None:
        prev = self._last.get(key, 0.0)
        if current > prev:
            bound.inc(current - prev, **labels)
        self._last[key] = current

    def update(self, store, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now < self._next:
            return
        self._next = now + self.min_interval_s
        self.g_resident.set(store.resident)
        self._delta(self.c_loads, ("loads",), store.loads_total)
        self._delta(self.c_evictions, ("evictions",), store.evictions_total)
        self._delta(self.c_miss, ("miss",), store.miss_total)
        for name, n in store.requests_total.items():
            self._delta(self.c_requests, ("req", name), n, adapter=name)
