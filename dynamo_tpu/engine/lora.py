"""Multi-tenant LoRA adapter store: batched heterogeneous adapters on
one base model (ROADMAP item 4; the S-LoRA / Punica technique done
TPU-idiomatically).

The runner holds ONE pair of stacked device pytrees per target
projection — ``A [L, S, d_in, r]`` / ``B [L, S, r, d_out]`` with
``S = max_adapters + 1`` slots (slot 0 is the base model: all-zero, no
delta) and every adapter's rank padded to a fixed ``lora_max_rank`` —
so the serving programs add the gathered low-rank correction
``x @ A[ids] @ B[ids]`` with STATIC shapes: heterogeneous adapters batch
into one decode window and the jit program count stays fixed (adapter
ids are data, not shape — zero recompiles per tenant mix).

This module owns the placement policy over those slots, KVBM-style:
host copies of every registered adapter are always kept (they are tiny —
a rank-8 adapter for an 8B model is ~10 MB), the device slots are the
constrained resource, and ``acquire`` hot-loads on miss with LRU
eviction over slots no live request references. ``pin`` exempts an
adapter from eviction entirely (latency-critical tenants). All device
work happens on the engine thread (``acquire``/``release`` are called
from admission/finish); ``register`` is pure host work and safe from
any thread.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

from dynamo_tpu.runtime.errors import AdapterNotFoundError, OverloadedError
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("lora")


class AdapterStore:
    def __init__(self, runner, max_adapters: int, max_rank: int):
        if max_adapters < 1:
            raise ValueError(f"max_adapters must be >= 1, got {max_adapters}")
        self.runner = runner
        self.max_adapters = max_adapters
        self.max_rank = max_rank
        # Canonical (d_in, d_out) per target projection — registration
        # validates host weights against these; the runner replicates
        # wk/wv columns itself when tp > num_kv_heads.
        self.target_shapes = runner.config.lora_target_shapes()
        self.num_layers = runner.canonical_spec.num_layers
        self._lock = threading.Lock()
        #: name -> {"weights": {key: (A, B)}, "rank": int, "path": str|None}
        self._registry: dict[str, dict] = {}
        #: device slot s (1-based) serves self._slots[s - 1].
        self._slots: list[str | None] = [None] * max_adapters
        self._slot_of: dict[str, int] = {}
        self._refs: dict[str, int] = collections.defaultdict(int)
        self._pinned: set[str] = set()
        self._lru_clock = 0
        self._last_used: dict[str, int] = {}
        # Plain-int telemetry (engine-thread friendly; the
        # AdapterMetricsUpdater turns these into dynamo_tpu_adapter_*
        # deltas on a throttle, docs/OBSERVABILITY.md "Adapters").
        self.loads_total = 0
        self.evictions_total = 0
        self.miss_total = 0
        self.requests_total: collections.Counter = collections.Counter()

    # -- host-side registry ---------------------------------------------------
    def register(self, name: str, path: str | None = None,
                 weights: dict | None = None) -> None:
        """Register an adapter by HF PEFT checkpoint dir or pre-loaded
        ``{key: (A [L, d_in, r], B [L, r, d_out])}`` host pytree. Host
        work only — the device upload happens lazily at first acquire
        (the hot-load path), so registration is cheap at any time."""
        if not name:
            raise ValueError("adapter name must be non-empty")
        if weights is None:
            if path is None:
                raise ValueError("register needs a path or weights")
            from dynamo_tpu.engine.weights import load_lora_weights
            weights = load_lora_weights(self.runner.canonical_spec, path,
                                        self.max_rank)
        rank = 0
        for key, (a, b) in weights.items():
            shape = self.target_shapes.get(key)
            if shape is None:
                raise ValueError(
                    f"adapter {name!r}: {key} is not a LoRA target for "
                    f"this model (targets: {sorted(self.target_shapes)})")
            d_in, d_out = shape
            want_a = (self.num_layers, d_in, self.max_rank)
            want_b = (self.num_layers, self.max_rank, d_out)
            if tuple(a.shape) != want_a or tuple(b.shape) != want_b:
                raise ValueError(
                    f"adapter {name!r}: {key} shapes {a.shape}/{b.shape} "
                    f"!= expected {want_a}/{want_b}")
            # Effective rank: trailing all-zero columns are padding.
            nz = np.flatnonzero(
                np.abs(np.asarray(a, np.float32)).sum(axis=(0, 1)))
            rank = max(rank, int(nz[-1]) + 1 if len(nz) else 0)
        with self._lock:
            replacing = name in self._registry
            self._registry[name] = {"weights": weights, "rank": rank,
                                    "path": path}
            if replacing and name in self._slot_of:
                # Live-reload: the resident copy is stale — re-upload in
                # place so in-flight acquires keep a consistent slot id.
                self._upload_locked(name, self._slot_of[name])
        log.info("adapter %r registered (rank %d%s)%s", name, rank,
                 f", {path}" if path else "",
                 " [live-reloaded]" if replacing else "")

    def registered(self, name: str) -> bool:
        with self._lock:
            return name in self._registry

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._registry)

    # -- device-slot placement (ENGINE THREAD) --------------------------------
    def _full_weights(self, name: str) -> dict:
        """The complete per-target host set for an upload: projections
        the checkpoint does not cover get zeros — a slot overwrite must
        never leave a previous tenant's deltas behind."""
        import ml_dtypes
        entry = self._registry[name]
        out = {}
        for key, (d_in, d_out) in self.target_shapes.items():
            pair = entry["weights"].get(key)
            if pair is None:
                pair = (np.zeros((self.num_layers, d_in, self.max_rank),
                                 ml_dtypes.bfloat16),
                        np.zeros((self.num_layers, self.max_rank, d_out),
                                 ml_dtypes.bfloat16))
            out[key] = pair
        return out

    def _upload_locked(self, name: str, slot: int) -> None:
        self.runner.set_adapter_slot(slot, self._full_weights(name))
        self.loads_total += 1

    def acquire(self, name: str) -> int:
        """Resolve an adapter name to its device slot id, hot-loading on
        miss (LRU eviction over unpinned slots no live request holds).
        Raises AdapterNotFoundError (unknown name — the frontend's 404)
        or OverloadedError (every slot busy — the router retries
        elsewhere / later). Pairs with ``release``."""
        with self._lock:
            if name not in self._registry:
                raise AdapterNotFoundError(
                    f"adapter {name!r} is not registered on this worker "
                    f"(serving: {sorted(self._registry) or 'none'})")
            self.requests_total[name] += 1
            self._lru_clock += 1
            self._last_used[name] = self._lru_clock
            slot = self._slot_of.get(name)
            if slot is None:
                slot = self._place_locked(name)
            self._refs[name] += 1
            return slot

    def _place_locked(self, name: str) -> int:
        self.miss_total += 1
        free = next((i for i, n in enumerate(self._slots) if n is None),
                    None)
        if free is None:
            victims = [n for n in self._slots
                       if n is not None and not self._refs[n]
                       and n not in self._pinned]
            if not victims:
                raise OverloadedError(
                    f"all {self.max_adapters} adapter slots are held by "
                    f"live or pinned adapters; cannot hot-load "
                    f"{name!r}", retry_after_s=1.0)
            victim = min(victims, key=lambda n: self._last_used.get(n, 0))
            free = self._slot_of.pop(victim) - 1
            self._slots[free] = None
            self.evictions_total += 1
            log.info("adapter %r evicted from slot %d (LRU) for %r",
                     victim, free + 1, name)
        slot = free + 1
        self._upload_locked(name, slot)
        self._slots[free] = name
        self._slot_of[name] = slot
        log.info("adapter %r hot-loaded into slot %d", name, slot)
        return slot

    def release(self, name: str) -> None:
        """Drop one live-request reference (engine thread, at slot
        finish). The adapter stays resident until LRU pressure."""
        with self._lock:
            if self._refs.get(name, 0) > 0:
                self._refs[name] -= 1

    def pin(self, name: str) -> None:
        """Exempt from LRU eviction (the KVBM pin discipline). Unknown
        names raise — a pin typo must not silently protect nothing."""
        with self._lock:
            if name not in self._registry:
                raise AdapterNotFoundError(f"cannot pin unknown adapter "
                                           f"{name!r}")
            self._pinned.add(name)

    def unpin(self, name: str) -> None:
        with self._lock:
            self._pinned.discard(name)

    def evict(self, name: str) -> bool:
        """Explicitly free an adapter's slot (admin). Refuses while live
        requests reference it; returns whether a slot was freed."""
        with self._lock:
            slot = self._slot_of.get(name)
            if slot is None or self._refs.get(name, 0):
                return False
            self._slot_of.pop(name)
            self._slots[slot - 1] = None
            self.evictions_total += 1
            return True

    @property
    def resident(self) -> int:
        return len(self._slot_of)

    def status(self) -> dict:
        """The /debug/kv "adapters" block (doctor check_adapters reads
        this through /debug/fleet)."""
        with self._lock:
            return {
                "max_adapters": self.max_adapters,
                "max_rank": self.max_rank,
                "registered": sorted(self._registry),
                "resident": {n: s for n, s in self._slot_of.items()},
                "pinned": sorted(self._pinned),
                "active_refs": {n: r for n, r in self._refs.items() if r},
                "loads_total": self.loads_total,
                "evictions_total": self.evictions_total,
                "miss_total": self.miss_total,
                "requests_total": dict(self.requests_total),
            }
