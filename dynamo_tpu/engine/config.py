"""Model and engine configuration.

ModelSpec covers the Llama family (Llama-2/3, Qwen2/2.5 via qkv_bias, TinyLlama)
— the architectures the reference's backends serve most (BASELINE.md config
ladder). MoE (Mixtral/DeepSeek) lands with the expert-parallel stage.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os


@dataclasses.dataclass
class ModelSpec:
    name: str = "tiny-test"
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    head_dim: int | None = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    qkv_bias: bool = False  # Qwen2 style
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 8192
    # MoE (Mixtral family): num_experts == 0 means dense FFN.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Weight-only quantization: None (bf16) or "int8" (engine/quant.py —
    # int8 storage, bf16 MXU compute; halves the weight-read roofline and
    # fits full llama-3-8b on one 16 GB v5e).
    quant: str | None = None

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    def num_params(self) -> int:
        """Approximate parameter count."""
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        d = self.head_dim
        attn = h * (self.num_heads * d) + 2 * h * (self.num_kv_heads * d) \
            + (self.num_heads * d) * h
        if self.num_experts:
            mlp = self.num_experts * 3 * h * i + h * self.num_experts
        else:
            mlp = 3 * h * i
        per_layer = attn + mlp + 2 * h
        embed = v * h * (1 if self.tie_word_embeddings else 2)
        return self.num_layers * per_layer + embed + h

    def kv_bytes_per_token(self, dtype_bytes: int = 2) -> int:
        """bf16-pool bytes per token (k+v, all layers/heads). Quantized
        KV pools add per-token scales — use EngineConfig.kv_token_bytes()
        for pool sizing so the int8 accounting stays honest."""
        return (2 * self.num_layers * self.num_kv_heads * self.head_dim
                * dtype_bytes)

    def weight_read_step_ms(self, tp: int = 1, pp: int = 1,
                            hbm_gbps: float | None = None) -> float:
        """Lower bound on a decode step for this spec's shard: one full
        read of the shard's bf16 weights from HBM. The single source of
        the bandwidth constant (bench roofline, auto window sizing,
        profiling) — override per part with DTPU_HBM_GBPS."""
        if hbm_gbps is None:
            hbm_gbps = float(os.environ.get("DTPU_HBM_GBPS", "819"))
        per_weight = 1.0 if self.quant == "int8" else 2.0
        shard_bytes = self.num_params() * per_weight / max(1, tp * pp)
        return shard_bytes / (hbm_gbps * 1e9) * 1e3

    @classmethod
    def from_hf_config(cls, path: str) -> "ModelSpec":
        """Build from a HF config.json (local dir or file)."""
        if os.path.isdir(path):
            path = os.path.join(path, "config.json")
        # dtpu: ignore[blocking-call-in-async] -- model-load startup I/O (HF config.json), never on the serving path
        with open(path) as fh:
            cfg = json.load(fh)
        return cls(
            name=cfg.get("_name_or_path", os.path.basename(os.path.dirname(path))),
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads",
                                 cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            qkv_bias=cfg.get("model_type") == "qwen2",
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            max_position_embeddings=cfg.get("max_position_embeddings", 8192),
            num_experts=cfg.get("num_local_experts", 0),
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        )


# Presets (shapes from the public model cards).
PRESETS: dict[str, ModelSpec] = {
    "tiny-test": ModelSpec(name="tiny-test", vocab_size=512, hidden_size=128,
                           intermediate_size=352, num_layers=2, num_heads=4,
                           num_kv_heads=2, max_position_embeddings=2048),
    "qwen2.5-0.5b": ModelSpec(name="qwen2.5-0.5b", vocab_size=151936,
                              hidden_size=896, intermediate_size=4864,
                              num_layers=24, num_heads=14, num_kv_heads=2,
                              rope_theta=1000000.0, qkv_bias=True,
                              tie_word_embeddings=True),
    # Llama-3-8B per-layer shapes with 8 of 32 layers: fits one v5e chip in
    # bf16 (~5.6 GiB) for single-chip benchmarking; full-model per-chip
    # numbers extrapolate by layer count.
    "llama-3-8b-L8": ModelSpec(name="llama-3-8b-L8", vocab_size=128256,
                               hidden_size=4096, intermediate_size=14336,
                               num_layers=8, num_heads=32, num_kv_heads=8,
                               rope_theta=500000.0),
    "llama-3-8b": ModelSpec(name="llama-3-8b", vocab_size=128256,
                            hidden_size=4096, intermediate_size=14336,
                            num_layers=32, num_heads=32, num_kv_heads=8,
                            rope_theta=500000.0),
    "llama-3-70b": ModelSpec(name="llama-3-70b", vocab_size=128256,
                             hidden_size=8192, intermediate_size=28672,
                             num_layers=80, num_heads=64, num_kv_heads=8,
                             rope_theta=500000.0),
}


@dataclasses.dataclass
class EngineConfig:
    model: ModelSpec = dataclasses.field(
        default_factory=lambda: PRESETS["tiny-test"])
    # KV paging
    page_size: int = 16  # tokens per page (= kv_cache_block_size)
    num_pages: int | None = None  # None => size from HBM budget
    hbm_kv_budget_frac: float = 0.6  # fraction of free HBM for KV after params
    max_pages_per_seq: int = 512
    # Batching
    max_num_seqs: int = 32
    max_prefill_tokens: int = 8192
    prefill_buckets: tuple = (128, 256, 512, 1024, 2048, 4096, 8192)
    # Decode steps per dispatched device program (tokens chain on-device;
    # the host sees sampled tokens once per window). Larger windows amortize
    # dispatch + readback latency at the cost of coarser stop-condition
    # granularity (up to window-1 wasted speculative tokens per finish).
    # "auto" sizes M from the model's weight-read step estimate so the
    # window PERIOD (M x step) lands near DTPU_WINDOW_TARGET_MS (default
    # 75 ms — keeps prefill admission gaps SLA-friendly): a 0.5B model
    # resolves to M=32, an unsharded 8B to M=4, an 8B shard at tp=4 to
    # M=12 (docs/PERF_NOTES.md sweep is where the target comes from).
    decode_window: int | str = 8
    # Microbatched pipeline-parallel PREFILL (model.prefill_forward_
    # pipelined): with pp > 1, whole-prompt prefill batches split into pp
    # microbatches flowing through the layer stages concurrently
    # (GPipe-style) instead of every stage idling while one batch
    # traverses the others' layers. Decode and history-chunk prefill keep
    # the layer-sharded path. Requires batch-bucket % pp == 0 to engage.
    pp_microbatch: bool = False
    # Ring attention for the sp axis (model.ring_causal_attention): K/V
    # blocks rotate around the sp ring via neighbor ppermute with an
    # online softmax instead of GSPMD's full K/V all-gather — peak
    # per-device K/V memory during a WHOLE-PROMPT (single-bucket)
    # prefill is one block. History-chunk prefills (prompts longer than
    # the largest bucket) still use the all-gather path, so size
    # prefill_buckets to the long-context target when enabling this.
    # Opt-in; the all-gather path stays the default.
    ring_attention: bool = False
    # Compile the decode-window program and the smallest prefill bucket
    # on the engine thread before serving, so a first short request
    # doesn't pay those XLA compile stalls (larger prefill buckets still
    # compile on first use). Workers enable this; tests skip it to keep
    # CPU suites fast.
    warmup_windows: bool = False
    # Extend warmup to the FULL prefill-bucket ladder including the
    # with-history (chunk) program variants. Without it the first long
    # prompt pays seconds of XLA compile per new bucket while every live
    # decode slot waits (the BENCH_r05 13.7 s TTFT-p99 outlier round).
    # Off by default so small-RAM CPU runs keep warmup cheap; serving
    # workers opt in (--warmup-prefill-ladder).
    warmup_prefill_ladder: bool = False
    # Stall-free chunked prefill (engine scheduler): per engine-loop
    # iteration at most this many prompt tokens are dispatched as prefill
    # chunks before the next decode window, so decode ITL interference
    # from a long prompt is bounded by ~one chunk's compute instead of
    # the whole prompt. "auto" derives the budget from the same
    # DTPU_WINDOW_TARGET_MS model as decode_window="auto" (one chunk ~
    # one window period). Env DTPU_PREFILL_CHUNK_TOKENS overrides either
    # form (docs/PERF_NOTES.md "Stall-free prefill").
    prefill_chunk_tokens: int | str = "auto"
    # Windows in flight before the host blocks on the oldest readback.
    # Each dispatch/readback pays a host<->device round trip (~100 ms
    # through a tunneled chip, ~100 us locally); depth D overlaps D of
    # them, so the steady-state window period approaches pure compute
    # (measured on v5e: depth 1->8 at M=8 = 3.6K->10.1K tok/s at bs32;
    # docs/PERF_NOTES.md).
    pipeline_depth: int = 8
    # Parallelism: tp shards heads/FFN (and MoE experts), pp shards the
    # stacked LAYER axis of parameters + KV cache across a "pp" mesh axis
    # (layer-sharded memory distribution; XLA streams each layer's weights
    # to where the activations are — microbatched true pipelining is a
    # future optimization), dp replicates.
    # sp shards the SEQUENCE axis of prefill activations/attention over a
    # mesh axis (all-to-all context parallelism via GSPMD: Q stays
    # sequence-sharded, XLA gathers K/V — the quadratic score term is
    # sp-sharded, which is what makes long-context prefill fit; a ring
    # attention kernel is the bandwidth optimization path). Decode is
    # unaffected (one token per step).
    tp: int = 1
    dp: int = 1
    pp: int = 1
    sp: int = 1
    # Numerics
    dtype: str = "bfloat16"
    # KV-cache quantization (engine/kv_quant.py): None (bf16 pages) or
    # "int8" — paged K/V stored int8 with per-token-per-head f32 scales,
    # dequant fused into the attention reads and quantize fused into the
    # page/window commit scatters. ~1.9x pool compression at head_dim
    # 64–128 => ~2x resident pages per HBM GB, and attention HBM traffic
    # at long context roughly halves. Composes with weight-only
    # ModelSpec.quant. Env DTPU_QUANT_KV overrides ("none" disables).
    quant_kv: str | None = None
    # Attention backend: "auto" | "pallas" | "xla"
    attention_backend: str = "auto"
    # KV tiering (reference KVBM G1..G3, block_manager.rs:72-82):
    # host_cache_pages > 0 enables the G2 host-DRAM block cache — pages
    # evicted from HBM are offloaded (async extract overlapping compute)
    # and prefix hits on spilled blocks are onboarded by upload instead of
    # recomputed. kv_disk_cache_dir adds the G3 disk tier behind it.
    host_cache_pages: int = 0
    kv_disk_cache_dir: str | None = None
    disk_cache_pages: int = 4096
    # KVBM placement policy (engine/kvbm.py): with a low watermark set,
    # the engine proactively demotes LRU inactive blocks to the host
    # tier whenever the HBM free list drops below low_watermark of the
    # pool, stopping at high_watermark (hysteresis; 0 = demote only
    # under allocation pressure, the pre-KVBM behavior). Needs
    # host_cache_pages > 0 to have somewhere to demote to. Env
    # DTPU_KV_WATERMARKS="low,high" overrides both.
    kv_demote_low_watermark: float = 0.0
    kv_demote_high_watermark: float = 0.0
    # Speculative decoding (reference SpecDecodeStats protocols.rs:32-56;
    # the reference delegates spec decode to its engines — here the
    # engine IS ours). "ngram" = prompt-lookup self-drafting: the window
    # program matches the sequence's trailing bigram against its own
    # on-device token history, proposes the spec_k tokens that followed
    # the previous occurrence, and VERIFIES them in one multi-token
    # forward — one weight read covers up to spec_k+1 positions, which
    # on an HBM-bound decode is up to a (spec_k+1)x ITL win on
    # repetitive text (summaries, code edits, RAG). GREEDY ONLY:
    # requests with temperature/logprobs/penalties/seeds are rejected
    # while this is enabled (rejection sampling for stochastic
    # equivalence is a later step). Off by default; plain serving is
    # untouched.
    spec_decode: str | None = None  # None | "ngram"
    spec_k: int = 3                 # drafts verified per step
    # SLA-aware admission (reference pre_deployment_profiling.md:36-38
    # role): with a TTFT budget set, admission projects the time to
    # prefill every already-admitted cold token plus the candidate's
    # (from the measured end-to-end prefill rate, EWMA over batched-
    # prefill readbacks) and defers the candidate in the waiting queue
    # while the projection exceeds the budget. One request is always
    # admissible when nothing else is in flight (a single over-budget
    # prompt must not starve). None disables the limiter.
    ttft_budget_ms: float | None = None
    # With a budget set, generate() additionally raises OverloadedError
    # (HTTP 503 at the frontend; the router retries elsewhere) when the
    # projected TTFT including QUEUED cold tokens exceeds budget x this
    # factor. 0 disables rejection: requests queue unboundedly instead.
    admission_reject_factor: float = 0.0
    # Engine-local brownout (runtime/overload.py has the frontend half):
    # at projected-TTFT pressure level >= this, speculative drafting is
    # suspended for decode windows until pressure drops — the verify
    # step's extra positions are overhead exactly when the engine is
    # behind. 0 disables the hook. Needs ttft_budget_ms to have a
    # pressure signal at all.
    brownout_spec_disable_level: int = 2
    # Multi-tenant batched LoRA (engine/lora.py, ROADMAP item 4): > 0
    # enables the adapter subsystem with this many RESIDENT device
    # adapter slots (slot 0 is always the base model — no delta). All
    # serving programs then add the gathered low-rank correction
    # x @ A[ids] @ B[ids] at every target projection, so HETEROGENEOUS
    # adapters batch into one decode window (the S-LoRA / Punica
    # technique, static-shaped so the jit program count stays fixed).
    # Registered adapters beyond the resident count hot-load on demand
    # with LRU eviction (host copies are always kept). 0 = disabled:
    # programs are byte-identical to the pre-LoRA engine.
    max_adapters: int = 0
    # Per-adapter rank is padded to this fixed max so A/B stacks keep
    # static shapes across heterogeneous adapters (checkpoints with a
    # larger rank are rejected at load).
    lora_max_rank: int = 8
    # Perf plane (engine/perf.py): the roofline fraction this deployment
    # is EXPECTED to achieve in steady-state decode — recorded into the
    # model card's runtime_config.extra and served on /debug/perf, so
    # doctor can WARN when the live perf_roofline_frac regresses > 20%
    # below it. None (default) disables the comparison; env
    # DTPU_EXPECTED_ROOFLINE_FRAC overrides at serving time.
    expected_roofline_frac: float | None = None

    def resolve_quant_kv(self) -> str | None:
        """The effective KV-pool quantization mode, with the DTPU_QUANT_KV
        env override applied (same layering as prefill_chunk_tokens)."""
        env = os.environ.get("DTPU_QUANT_KV")
        if env is not None:
            env = env.strip().lower()
            return None if env in ("", "none", "off", "bf16") else env
        return self.quant_kv

    def kvbm_policy(self):
        """The KVBM tier policy for this config (engine/kvbm.py), with
        the DTPU_KV_WATERMARKS="low,high" env override applied (same
        layering as the other engine knobs)."""
        from dynamo_tpu.engine.kvbm import KvbmPolicy
        low, high = (self.kv_demote_low_watermark,
                     self.kv_demote_high_watermark)
        env = os.environ.get("DTPU_KV_WATERMARKS")
        if env:
            parts = [p for p in env.replace(",", " ").split() if p]
            low = float(parts[0])
            high = float(parts[1]) if len(parts) > 1 else 0.0
        return KvbmPolicy(low_watermark=low, high_watermark=high)

    def kv_token_bytes(self) -> int:
        """Per-token bytes in the device KV pool (k+v, all layers/heads):
        bf16 = 2 bytes/value; int8 = 1 byte/value + a 4-byte f32 scale
        per (layer, head, token). The single source for pool sizing and
        the perf plane's HBM KV ledger."""
        m = self.model
        if self.resolve_quant_kv() == "int8":
            per_head = m.head_dim + 4  # KV_SCALE_BYTES
        else:
            per_head = 2 * m.head_dim
        return 2 * m.num_layers * m.num_kv_heads * per_head

    def lora_target_shapes(self) -> dict[str, tuple[int, int]]:
        """(d_in, d_out) per LoRA target projection for this model —
        the attention projections always, the dense MLP projections when
        the model is dense (MoE expert weights are not adapter targets:
        PEFT Mixtral checkpoints conventionally target attention only).
        The single source for stack shapes in the runner, the loader's
        padding, and the store's host-side validation."""
        m = self.model
        d = m.head_dim
        shapes = {
            "wq": (m.hidden_size, m.num_heads * d),
            "wk": (m.hidden_size, m.num_kv_heads * d),
            "wv": (m.hidden_size, m.num_kv_heads * d),
            "wo": (m.num_heads * d, m.hidden_size),
        }
        if not m.num_experts:
            shapes["w_gate"] = (m.hidden_size, m.intermediate_size)
            shapes["w_up"] = (m.hidden_size, m.intermediate_size)
            shapes["w_down"] = (m.intermediate_size, m.hidden_size)
        return shapes

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return self.prefill_buckets[-1]

    def resolve_decode_window(self) -> int:
        """Resolve ``decode_window="auto"`` to a concrete M.

        TPU-first sizing: a decode step is bounded below by reading this
        shard's weights once from HBM; the per-dispatch host overhead is
        ~constant. Pick M so the window period M x (step estimate) hits
        DTPU_WINDOW_TARGET_MS — long enough to amortize dispatch, short
        enough that prefill admission between windows keeps p99 TTFT
        inside the SLA (bench sweep in docs/PERF_NOTES.md)."""
        if isinstance(self.decode_window, int):
            if self.decode_window < 1:
                raise ValueError(
                    f"decode_window must be >= 1, got {self.decode_window}")
            return self.decode_window
        if self.decode_window != "auto":
            raise ValueError(
                f"decode_window must be an int or 'auto', "
                f"got {self.decode_window!r}")
        target_ms = float(os.environ.get("DTPU_WINDOW_TARGET_MS", "75"))
        step_ms = self.model.weight_read_step_ms(self.tp, self.pp) \
            + 1.0  # + host/dispatch overhead
        raw = target_ms / step_ms
        nice = (1, 2, 4, 8, 12, 16, 24, 32, 48, 64)
        return min(nice, key=lambda m: abs(m - raw))

    def resolve_prefill_chunk_tokens(self) -> int:
        """Resolve ``prefill_chunk_tokens="auto"`` to a concrete budget.

        Cost model: a prefill chunk of n tokens costs ~max(1, n/knee)
        weight-read periods — below the knee the chunk is bandwidth-bound
        (one weight read regardless of n), above it compute-bound (linear
        in n). knee ~= the chip's flops/byte ratio (~240 for v5e bf16);
        DTPU_PREFILL_KNEE_TOK overrides per part. The budget is sized so
        one iteration's chunk work costs about one DTPU_WINDOW_TARGET_MS
        window period, then rounded DOWN to a prefill bucket (chunks pad
        to bucket shapes, so a between-buckets budget would pad up and
        overshoot the target)."""
        val = self.prefill_chunk_tokens
        env = os.environ.get("DTPU_PREFILL_CHUNK_TOKENS")
        if env:
            val = env if env.strip() == "auto" else int(env)
        if not isinstance(val, str):
            if val < 1:
                raise ValueError(
                    f"prefill_chunk_tokens must be >= 1, got {val}")
            return max(self.page_size, int(val))
        if val != "auto":
            raise ValueError(
                f"prefill_chunk_tokens must be an int or 'auto', "
                f"got {val!r}")
        target_ms = float(os.environ.get("DTPU_WINDOW_TARGET_MS", "75"))
        step_ms = self.model.weight_read_step_ms(self.tp, self.pp)
        knee = float(os.environ.get("DTPU_PREFILL_KNEE_TOK", "256"))
        raw = int(knee * max(1.0, target_ms / max(step_ms, 1e-6)))
        raw = min(raw, self.max_prefill_tokens, self.prefill_buckets[-1])
        fit = [b for b in self.prefill_buckets if b <= raw]
        return max(self.page_size, fit[-1] if fit else raw)

    @property
    def max_model_len(self) -> int:
        return self.max_pages_per_seq * self.page_size
