"""dynamo_tpu — TPU-native distributed LLM inference-serving framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of NVIDIA Dynamo
(reference: /root/reference, surveyed in SURVEY.md): OpenAI-compatible frontend,
distributed runtime with discovery/leases/streaming request plane, KV-cache-aware
routing, disaggregated prefill/decode with chip-to-chip KV transfer, multi-tier KV
block management, and a native JAX continuous-batching engine with Pallas paged
attention (the reference delegates the engine to vLLM/SGLang/TRT-LLM; we supply it).
"""

__version__ = "0.1.0"
