"""Unified launcher: one process, pluggable input and output.

Capability parity with reference dynamo-run (launch/dynamo-run/src/
lib.rs:19-92, input adapters entrypoint/input/{http,grpc,text,batch}.rs):
``python -m dynamo_tpu.launch in=<http|grpc|text|batch> out=<tpu|
mocker|echo> [--model ...]`` assembles the whole pipeline statically —
tokenizer, preprocessor, detokenizing backend, engine — with no
coordinator, no registration, no network hop between frontend and engine.
``out=dyn`` connects to a coordinator instead and serves whatever workers
register (the distributed mode the separate frontend/worker mains also
provide).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, ServedModel
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import (DEFAULT_CHAT_TEMPLATE,
                                       ModelDeploymentCard, ModelEntry)
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols import ChatCompletionRequest
from dynamo_tpu.llm.tokenizer import Tokenizer, make_test_tokenizer
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("launch")


def parse_args(argv=None) -> argparse.Namespace:
    argv = list(sys.argv[1:] if argv is None else argv)
    io = {"in": "http", "out": "tpu"}
    rest = []
    for a in argv:
        if a.startswith("in=") or a.startswith("out="):
            k, v = a.split("=", 1)
            io[k] = v
        else:
            rest.append(a)
    parser = argparse.ArgumentParser(
        description="dynamo-tpu unified launcher (in=http|text "
                    "out=tpu|mocker|echo|dyn)")
    parser.add_argument("--model", default="tiny-test")
    parser.add_argument("--model-name", default=None)
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument("--http-host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--num-pages", type=int, default=None)
    parser.add_argument("--max-num-seqs", type=int, default=32)
    parser.add_argument("--context-length", type=int, default=8192)
    # Engine knobs shared with the worker (backends.tpu.build_engine_config).
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--max-pages-per-seq", type=int, default=512)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--attention-backend", default="auto",
                        choices=["auto", "pallas", "xla"])
    from dynamo_tpu.backends.tpu import _chunk_arg, _window_arg
    parser.add_argument("--decode-window", default="auto", type=_window_arg,
                        help="positive int or 'auto' (size from the model's "
                             "weight-read step estimate)")
    parser.add_argument("--pipeline-depth", type=int, default=4)
    parser.add_argument("--prefill-chunk-tokens", default="auto",
                        type=_chunk_arg,
                        help="stall-free chunked prefill budget per "
                             "engine-loop iteration (int or 'auto')")
    parser.add_argument("--warmup-prefill-ladder", action="store_true",
                        help="pre-compile every prefill bucket (incl. "
                             "chunk/history variants) at startup")
    parser.add_argument("--quant", default=None, choices=["int8"],
                        help="weight-only int8 quantization (halves "
                             "weight HBM reads)")
    parser.add_argument("--quant-kv", default=None, choices=["int8"],
                        help="int8 KV cache: ~2x pages per HBM GB, "
                             "dequant fused into attention; composes "
                             "with --quant (DTPU_QUANT_KV overrides)")
    parser.add_argument("--host-cache-pages", type=int, default=0)
    parser.add_argument("--kv-disk-cache-dir", default=None)
    parser.add_argument("--lora", action="append", default=[],
                        metavar="NAME=PATH",
                        help="out=tpu: serve a LoRA adapter as its own "
                             "model name on the in-process engine "
                             "(HF PEFT checkpoint dir; repeatable)")
    parser.add_argument("--max-adapters", type=int, default=None)
    parser.add_argument("--max-lora-rank", type=int, default=8)
    parser.add_argument("--coordinator-url", default=None,
                        help="out=dyn: control plane to discover workers on")
    parser.add_argument("--tool-call-parser", default=None)
    parser.add_argument("--reasoning-parser", default=None)
    parser.add_argument("--input-file", default=None,
                        help="in=batch: JSONL of prompts ({'prompt': ...} or "
                             "{'messages': [...]}, optional max_tokens)")
    parser.add_argument("--output-file", default=None,
                        help="in=batch: JSONL results path "
                             "(default <input-file>.results.jsonl)")
    parser.add_argument("--batch-concurrency", type=int, default=8,
                        help="in=batch: max in-flight requests")
    parser.add_argument("--batch-max-tokens", type=int, default=128,
                        help="in=batch: default max_tokens per prompt")
    # SLO plane + per-request accounting (runtime/slo.py,
    # docs/OBSERVABILITY.md); fine-grained knobs via DTPU_SLO_*.
    parser.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                        help="TTFT SLO target (99%% within this budget)")
    parser.add_argument("--request-log", default=None,
                        help="append per-request accounting records as "
                             "JSONL here (scripts/slo_report.py)")
    args = parser.parse_args(rest)
    args.input = io["in"]
    args.output = io["out"]
    if args.input not in ("http", "grpc", "text", "batch"):
        parser.error(f"in= must be http|grpc|text|batch, got {args.input!r}")
    if args.input == "batch" and not args.input_file:
        parser.error("in=batch requires --input-file")
    if args.output not in ("tpu", "mocker", "echo", "dyn"):
        parser.error(f"out= must be tpu|mocker|echo|dyn, got {args.output!r}")
    return args


def _build_engine(args, metrics_registry=None):
    if args.output == "echo":
        from dynamo_tpu.llm.engines import EchoEngine
        return EchoEngine(token_delay_s=0.005), make_test_tokenizer()
    if args.output == "mocker":
        from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine
        eng = MockerEngine(MockerConfig(speedup_ratio=10.0))
        eng.start()
        return eng, make_test_tokenizer()
    # out=tpu: the real engine, in-process.
    from dynamo_tpu.backends.tpu import build_engine_config
    from dynamo_tpu.engine.engine import TPUEngine
    from dynamo_tpu.engine.weights import load_hf_weights
    cfg = build_engine_config(args)
    ckpt = args.resolved_checkpoint
    params = None
    if ckpt is not None:
        params = load_hf_weights(cfg.model, ckpt)
        tokenizer = Tokenizer.from_pretrained_dir(ckpt)
    elif args.tokenizer:
        tokenizer = Tokenizer.from_file(args.tokenizer)
    else:
        tokenizer = make_test_tokenizer()
    engine = TPUEngine(cfg, params=params,
                       metrics_registry=metrics_registry)
    engine.start()
    return engine, tokenizer


def build_local_served(args, metrics_registry=None
                       ) -> tuple[ServedModel, object]:
    """Static pipeline: Preprocessor -> Backend -> engine, no network.
    With ``--lora``, the adapters register on the engine and each
    adapter name becomes its own ServedModel (attached as
    ``served.adapter_served``) whose card carries the (base, adapter)
    binding — the same resolution the distributed frontend does from
    discovered cards."""
    if getattr(args, "lora", None) and args.output != "tpu":
        raise SystemExit("--lora needs the real engine (out=tpu)")
    engine, tokenizer = _build_engine(args, metrics_registry)
    name = args.model_name or os.path.basename(args.model.rstrip("/"))
    card = ModelDeploymentCard(
        name=name, chat_template=DEFAULT_CHAT_TEMPLATE,
        context_length=args.context_length,
        tool_call_parser=args.tool_call_parser,
        reasoning_parser=args.reasoning_parser)
    entry = ModelEntry(model_name=name, namespace="local", component="local",
                       endpoint="generate", model_type="chat", card=card)
    backend = Backend(tokenizer, inner=engine)
    pre = OpenAIPreprocessor(card, tokenizer, inner=backend)
    served = ServedModel(entry, pre, client=None, router=None)
    served.adapter_served = []
    for item in getattr(args, "lora", None) or []:
        lname, sep, path = str(item).partition("=")
        if not sep or not lname or not path:
            raise SystemExit(f"--lora expects NAME=PATH, got {item!r}")
        engine.register_adapter(lname, path=path)
        from dynamo_tpu.llm.model_card import ModelRuntimeConfig
        acard = ModelDeploymentCard(
            name=lname, chat_template=DEFAULT_CHAT_TEMPLATE,
            context_length=args.context_length,
            tool_call_parser=args.tool_call_parser,
            reasoning_parser=args.reasoning_parser,
            runtime_config=ModelRuntimeConfig(
                extra={"lora_base": name, "adapter": lname}))
        aentry = ModelEntry(model_name=lname, namespace="local",
                            component="local", endpoint="generate",
                            model_type="chat", card=acard)
        apre = OpenAIPreprocessor(acard, tokenizer, inner=backend)
        served.adapter_served.append(
            ServedModel(aentry, apre, client=None, router=None))
    return served, engine


async def run_text_repl(served: ServedModel) -> None:
    """in=text: an interactive prompt loop on stdin (dynamo-run's text
    input)."""
    loop = asyncio.get_running_loop()
    print("dynamo-tpu text console — empty line or EOF exits", flush=True)
    while True:
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line or not line.strip():
            return
        req = ChatCompletionRequest(
            model=served.name,
            messages=[{"role": "user", "content": line.strip()}],
            max_tokens=64, stream=True)
        async for chunk in served.preprocessor.generate(req, Context()):
            for choice in chunk.get("choices", []):
                piece = choice.get("delta", {}).get("content")
                if piece:
                    print(piece, end="", flush=True)
        print(flush=True)


async def run_batch(served: ServedModel, args) -> None:
    """in=batch: run a JSONL file of prompts through the pipeline with
    bounded concurrency, write one JSONL result per prompt (reference
    entrypoint/input/batch.rs: file of prompts -> completions + timing)."""
    import json
    import time

    jobs = []
    # One-shot batch-mode input read before any generation task exists;
    # nothing else shares the loop yet.
    # dtpu: ignore[blocking-call-in-async] -- one-shot startup I/O
    with open(args.input_file, "r", encoding="utf-8") as fh:
        for line in fh:
            if not line.strip():
                continue
            try:
                jobs.append(json.loads(line))
            except json.JSONDecodeError as exc:
                jobs.append(ValueError(f"unparseable JSONL line: {exc}"))
    out_path = args.output_file or args.input_file + ".results.jsonl"
    sem = asyncio.Semaphore(args.batch_concurrency)

    async def one(idx: int, job) -> dict:
        # Per-job isolation: a malformed line or a failed generation yields
        # an error row instead of losing the rest of the batch.
        try:
            if isinstance(job, Exception):
                raise job
            if not isinstance(job, dict):
                raise ValueError(f"line is {type(job).__name__}, "
                                 "expected a JSON object")
            messages = job.get("messages") or [
                {"role": "user", "content": job.get("prompt", "")}]
            req = ChatCompletionRequest(
                model=served.name, messages=messages,
                max_tokens=int(job.get("max_tokens", args.batch_max_tokens)),
                temperature=job.get("temperature", 0.0), stream=True,
                stream_options={"include_usage": True})
            text, n_tokens, finish = [], 0, None
            async with sem:
                t0 = time.monotonic()
                t_first = None
                async for chunk in served.preprocessor.generate(req,
                                                                Context()):
                    # Token counts come from the usage block (detokenizer
                    # delta chunks are not 1:1 with tokens).
                    usage = chunk.get("usage")
                    if usage:
                        n_tokens = usage.get("completion_tokens", n_tokens)
                    for choice in chunk.get("choices", []):
                        piece = choice.get("delta", {}).get("content")
                        if piece:
                            if t_first is None:
                                t_first = time.monotonic()
                            text.append(piece)
                        if choice.get("finish_reason"):
                            finish = choice["finish_reason"]
                elapsed = time.monotonic() - t0
            return {"index": idx, "text": "".join(text),
                    "finish_reason": finish, "tokens": n_tokens,
                    "elapsed_s": round(elapsed, 4),
                    "ttft_s": round((t_first or t0) - t0, 4)}
        except Exception as exc:  # noqa: BLE001 — keep the batch going
            return {"index": idx, "error": f"{type(exc).__name__}: {exc}",
                    "tokens": 0}

    t0 = time.monotonic()
    results = await asyncio.gather(*[one(i, j) for i, j in enumerate(jobs)])
    elapsed = time.monotonic() - t0
    # dtpu: ignore[blocking-call-in-async] -- results dump after the batch
    with open(out_path, "w", encoding="utf-8") as fh:
        for r in results:
            fh.write(json.dumps(r) + "\n")
    total_tokens = sum(r["tokens"] for r in results)
    n_errors = sum(1 for r in results if "error" in r)
    print(json.dumps({
        "batch_prompts": len(jobs), "errors": n_errors,
        "output_tokens": total_tokens,
        "elapsed_s": round(elapsed, 3),
        "tok_s": round(total_tokens / elapsed, 1) if elapsed else 0.0,
        "results": out_path}), flush=True)


async def run(args) -> None:
    if args.output == "dyn":
        cfg = RuntimeConfig.from_settings()
        if args.coordinator_url:
            cfg.coordinator_url = args.coordinator_url
        runtime = await DistributedRuntime.from_settings(cfg)
        manager = ModelManager()
        watcher = ModelWatcher(runtime, manager)
        await watcher.start()
        engine = None
    else:
        runtime = await DistributedRuntime.detached(RuntimeConfig())
        manager = ModelManager()
        served, engine = build_local_served(
            args, runtime.metrics.namespace("local").component(args.output))
        manager.models[served.name] = served
        for extra in getattr(served, "adapter_served", []):
            manager.models[extra.name] = extra
        watcher = None
    # SLO plane + accounting ledger + flight-bundle context: the static
    # pipeline gets the same decision-grade observability the
    # distributed frontend does (DTPU_SLO_* / [slo] TOML configurable).
    from dynamo_tpu.frontend.main import init_observability
    if args.slo_ttft_p99_ms is not None:
        runtime.config.slo.ttft_p99_ms = args.slo_ttft_p99_ms
    if args.request_log is not None:
        runtime.config.slo.request_log_path = args.request_log
    init_observability(runtime.config, runtime)
    try:
        if args.input in ("text", "batch"):
            if args.output == "dyn":
                raise SystemExit(f"in={args.input} requires a local out= "
                                 "engine")
            if args.input == "text":
                await run_text_repl(served)
            else:
                await run_batch(served, args)
            return
        if args.input == "grpc":
            from dynamo_tpu.grpc.kserve import make_server
            server, port = make_server(manager, host=args.http_host,
                                       port=args.http_port)
            await server.start()
            print(f"LAUNCH_READY in=grpc out={args.output} port={port}",
                  flush=True)
            await runtime.wait_for_shutdown()
            await server.stop(grace=1.0)
            return
        # Overload defense (runtime/overload.py): same adaptive
        # admission the distributed frontend gets, DTPU_OVERLOAD_*
        # configurable (DTPU_OVERLOAD_ENABLED=0 disables).
        from dynamo_tpu.runtime.overload import AdaptiveLimiter
        ov = runtime.config.overload
        limiter = (AdaptiveLimiter(ov, metrics=runtime.metrics)
                   if ov.enabled else None)
        service = HttpService(runtime, manager, host=args.http_host,
                              port=args.http_port, overload=limiter)
        await service.start()
        print(f"LAUNCH_READY in={args.input} out={args.output} "
              f"port={service.port}", flush=True)
        await runtime.wait_for_shutdown()
        await service.stop()
    finally:
        if watcher is not None:
            await watcher.stop()
        if engine is not None:
            stop = getattr(engine, "stop", None)
            if stop is not None:
                res = stop()
                if asyncio.iscoroutine(res):
                    await res
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
