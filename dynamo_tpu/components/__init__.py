"""Standalone service components (reference components/: planner lives in
dynamo_tpu.planner; the metrics aggregator here)."""
