"""Metrics aggregator component.

Capability parity with reference components/metrics: subscribes to the
workers' ForwardPassMetrics pub/sub plane for one or more components and
exposes the fleet view as Prometheus gauges (per-worker and aggregate) on
an HTTP endpoint — the scrape target Grafana/planner dashboards read.

Run: ``python -m dynamo_tpu.components.metrics --components tpu,prefill
--port 9091``
"""

from __future__ import annotations

import argparse
import asyncio

from aiohttp import web

from dynamo_tpu.llm.kv_router.protocols import (ForwardPassMetrics,
                                                load_metrics_subject)
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("metrics_aggregator")


class MetricsAggregator:
    def __init__(self, runtime, namespace: str, components: list[str],
                 stale_s: float = 30.0):
        self._runtime = runtime
        self.namespace = namespace
        self.components = components
        self.stale_s = stale_s
        self._subs: list = []
        self._tasks: list[asyncio.Task] = []
        # (component, worker) -> (last_update_monotonic, metrics)
        self._last: dict[tuple[str, str], tuple[float, ForwardPassMetrics]] \
            = {}
        m = runtime.metrics.namespace(namespace)
        self._g_fleet_active = m.gauge(
            "fleet_active_slots", "Active slots across live workers",
            ["component"])
        self._g_fleet_waiting = m.gauge(
            "fleet_waiting_requests", "Queued requests across live workers",
            ["component"])
        self._g_fleet_workers = m.gauge(
            "fleet_live_workers", "Workers reporting within the staleness "
            "window", ["component"])
        self._g_active = m.gauge(
            "worker_active_slots", "Active request slots per worker",
            ["component", "worker"])
        self._g_waiting = m.gauge(
            "worker_waiting_requests", "Queued requests per worker",
            ["component", "worker"])
        self._g_kv = m.gauge(
            "worker_kv_usage", "KV pool usage fraction per worker",
            ["component", "worker"])
        self._g_hit = m.gauge(
            "worker_prefix_hit_rate", "Prefix cache hit rate per worker",
            ["component", "worker"])

    async def start(self) -> None:
        client = self._runtime.require_coordinator()
        for comp in self.components:
            sub = await client.subscribe(
                load_metrics_subject(self.namespace, comp))
            self._subs.append(sub)
            self._tasks.append(asyncio.create_task(self._intake(comp, sub)))
        self._tasks.append(asyncio.create_task(self._reap_loop()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            await s.cancel()

    async def _intake(self, comp: str, sub) -> None:
        async for msg in sub:
            try:
                m = ForwardPassMetrics.from_wire(msg["payload"])
            except (KeyError, TypeError, ValueError):
                continue
            worker = f"{m.worker_id or 0:x}"
            self._last[(comp, worker)] = (asyncio.get_running_loop().time(),
                                          m)
            ws, ks = m.worker_stats, m.kv_stats
            self._g_active.set(ws.request_active_slots, component=comp,
                               worker=worker)
            self._g_waiting.set(ws.num_requests_waiting, component=comp,
                                worker=worker)
            self._g_kv.set(ks.gpu_cache_usage_perc, component=comp,
                           worker=worker)
            self._g_hit.set(ks.gpu_prefix_cache_hit_rate, component=comp,
                            worker=worker)
            self._refresh_fleet()

    def _refresh_fleet(self) -> None:
        """Fleet totals over non-stale workers; stale workers' per-worker
        series are zeroed so a dead worker's last load doesn't haunt
        dashboards forever."""
        now = asyncio.get_running_loop().time()
        totals: dict[str, list[int]] = {c: [0, 0, 0] for c in self.components}
        for (comp, worker), (t, m) in list(self._last.items()):
            if now - t > self.stale_s:
                self._g_active.set(0, component=comp, worker=worker)
                self._g_waiting.set(0, component=comp, worker=worker)
                self._g_kv.set(0, component=comp, worker=worker)
                self._g_hit.set(0, component=comp, worker=worker)
                del self._last[(comp, worker)]
                continue
            tot = totals.setdefault(comp, [0, 0, 0])
            tot[0] += m.worker_stats.request_active_slots
            tot[1] += m.worker_stats.num_requests_waiting
            tot[2] += 1
        for comp, (active, waiting, n) in totals.items():
            self._g_fleet_active.set(active, component=comp)
            self._g_fleet_waiting.set(waiting, component=comp)
            self._g_fleet_workers.set(n, component=comp)

    async def _reap_loop(self) -> None:
        while True:
            await asyncio.sleep(max(1.0, self.stale_s / 3))
            self._refresh_fleet()


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu metrics aggregator")
    p.add_argument("--namespace", default=None)
    p.add_argument("--components", default="tpu",
                   help="comma-separated worker components to aggregate")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--coordinator-url", default=None)
    return p.parse_args(argv)


async def run(args) -> None:
    cfg = RuntimeConfig.from_settings()
    if args.coordinator_url:
        cfg.coordinator_url = args.coordinator_url
    if args.namespace:
        cfg.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(cfg)
    agg = MetricsAggregator(runtime, cfg.namespace,
                            [c.strip() for c in args.components.split(",")
                             if c.strip()])
    await agg.start()

    async def metrics_route(_req):
        return web.Response(body=runtime.metrics.expose(),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics_route)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
    print(f"METRICS_AGGREGATOR_READY port={port}", flush=True)
    try:
        await runtime.wait_for_shutdown()
    finally:
        await agg.stop()
        await runner.cleanup()
        await runtime.close()


def main() -> None:
    asyncio.run(run(parse_args()))


if __name__ == "__main__":
    main()
