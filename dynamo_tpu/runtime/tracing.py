"""End-to-end request tracing: spans, ring-buffer recorder, exporters.

Capability parity with the reference's W3C trace-context threading
(lib/runtime/src/logging.rs:111-175) plus what the Rust side delegates to
the OTEL SDK: actually *recording* spans so "why was this request slow?"
is answerable without a debugger. Pieces:

- ``span(name, ctx=..., **attrs)`` — a context manager (sync AND async)
  that records start/end monotonic+wall timestamps, parent/child links
  (via a contextvar, or an explicit request ``Context``), status
  (ok/error/cancelled), and attributes.
- ``SpanRecorder`` — a bounded in-process ring buffer with per-trace
  assembly and two exporters: Chrome trace-event JSON (loadable in
  Perfetto / ``chrome://tracing``) and OTLP-JSON-shaped dicts.
- a module-global recorder (``DTPU_TRACING=0`` disables, default
  capacity ``DTPU_TRACE_CAPACITY=8192``) with a no-op fast path: when
  disabled, ``span()`` returns a shared singleton and ``add()`` returns
  immediately — zero allocations on the per-token path.
- ``phase_metrics(registry)`` — the per-phase latency histograms
  (queue wait / prefill / decode / KV transfer) every span-producing
  site also feeds, so SLO dashboards get phase breakdowns, not just
  edge TTFT/ITL.
- ``capture_profile(...)`` — the on-demand ``jax.profiler`` hook behind
  ``POST /debug/profile``, degrading to a span-recorder dump when JAX
  profiling is unavailable.

Threading: spans are recorded from the event loop AND the engine thread;
the recorder takes a lock per record (one append per span, not per
token). Contextvar parenting is per-thread/per-task by construction;
engine-thread spans link explicitly via (trace_id, parent_id) instead.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import json
import os
import threading
import time

from dynamo_tpu.runtime.logging import (current_trace, generate_span_id,
                                        generate_trace_id, get_logger)

log = get_logger("tracing")

# The active span for the current task/thread (parenting).
current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dtpu_span", default=None)


class Span:
    """One recorded operation. ``end_mono`` is None while open."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "start_wall", "start_mono", "end_mono", "status", "attrs",
                 "thread_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: str | None, name: str,
                 start_wall: float, start_mono: float,
                 attrs: dict | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.start_wall = start_wall
        self.start_mono = start_mono
        self.end_mono: float | None = None
        self.status = "ok"
        self.attrs = attrs
        self.thread_id = threading.get_ident()

    @property
    def duration_s(self) -> float:
        end = self.end_mono if self.end_mono is not None else self.start_mono
        return end - self.start_mono

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "start_wall": self.start_wall,
            "start_mono": self.start_mono,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": self.attrs or {},
        }


class SpanRecorder:
    """Bounded ring buffer of finished spans with per-trace assembly."""

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self.capacity = capacity
        self.enabled = enabled
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.dropped = 0  # spans evicted by the ring (observability)

    # -- recording ------------------------------------------------------------
    def record(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped += 1
            self._spans.append(span)

    def add(self, name: str, trace_id: str, parent_id: str | None,
            start_mono: float, end_mono: float, status: str = "ok",
            attrs: dict | None = None) -> str | None:
        """Record an already-timed span (engine-thread hot paths measure
        their own intervals; no contextvar juggling). Returns the span id,
        or None when disabled (fast path: one attribute read, no
        allocation)."""
        if not self.enabled:
            return None
        now_mono = time.monotonic()
        span = Span(trace_id=trace_id, span_id=generate_span_id(),
                    parent_span_id=parent_id, name=name,
                    start_wall=time.time() - (now_mono - start_mono),
                    start_mono=start_mono, attrs=attrs)
        span.end_mono = end_mono
        span.status = status
        self.record(span)
        return span.span_id

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- per-trace assembly ---------------------------------------------------
    def trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            spans = [s for s in self._spans if s.trace_id == trace_id]
        spans.sort(key=lambda s: s.start_mono)
        return spans

    def recent(self, limit: int = 50) -> list[dict]:
        """Newest-first index of recorded traces (for /debug/traces/recent)."""
        with self._lock:
            snapshot = list(self._spans)
        by_trace: dict[str, list[Span]] = {}
        for s in snapshot:
            by_trace.setdefault(s.trace_id, []).append(s)
        out = []
        for trace_id, spans in by_trace.items():
            ids = {s.span_id for s in spans}
            roots = [s for s in spans
                     if s.parent_span_id is None
                     or s.parent_span_id not in ids]
            root = min(roots or spans, key=lambda s: s.start_mono)
            t0 = min(s.start_mono for s in spans)
            t1 = max(s.end_mono or s.start_mono for s in spans)
            out.append({
                "trace_id": trace_id,
                "root": root.name,
                "start_wall": root.start_wall,
                "spans": len(spans),
                "duration_s": t1 - t0,
                "status": ("error" if any(s.status == "error" for s in spans)
                           else "ok"),
            })
        out.sort(key=lambda e: e["start_wall"], reverse=True)
        return out[:limit]

    # -- exporters ------------------------------------------------------------
    def export_chrome(self, trace_id: str | None = None) -> dict:
        """Chrome trace-event JSON ("X" complete events, microsecond
        timestamps relative to the earliest span) — drop the payload in
        Perfetto or chrome://tracing."""
        spans = (self.trace(trace_id) if trace_id is not None
                 else sorted(self._snapshot(), key=lambda s: s.start_mono))
        events = []
        if spans:
            base = min(s.start_mono for s in spans)
            pid = os.getpid()
            for s in spans:
                args = dict(s.attrs or {})
                args["trace_id"] = s.trace_id
                args["span_id"] = s.span_id
                if s.parent_span_id:
                    args["parent_span_id"] = s.parent_span_id
                if s.status != "ok":
                    args["status"] = s.status
                events.append({
                    "name": s.name,
                    "ph": "X",
                    "ts": (s.start_mono - base) * 1e6,
                    "dur": s.duration_s * 1e6,
                    "pid": pid,
                    "tid": s.thread_id,
                    "cat": "dtpu",
                    "args": args,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_otlp(self, trace_id: str | None = None) -> dict:
        """OTLP/JSON-shaped dict (ExportTraceServiceRequest): importable
        by any OTLP-JSON consumer without an OTEL SDK dependency."""
        spans = (self.trace(trace_id) if trace_id is not None
                 else sorted(self._snapshot(), key=lambda s: s.start_mono))
        status_code = {"ok": 1, "error": 2, "cancelled": 2}
        otlp_spans = []
        for s in spans:
            start_ns = int(s.start_wall * 1e9)
            otlp_spans.append({
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_span_id or "",
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(start_ns + int(s.duration_s * 1e9)),
                "status": {"code": status_code.get(s.status, 0)},
                "attributes": [
                    {"key": k, "value": _otlp_value(v)}
                    for k, v in (s.attrs or {}).items()
                ],
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": "dynamo-tpu"}}]},
            "scopeSpans": [{
                "scope": {"name": "dynamo_tpu.runtime.tracing"},
                "spans": otlp_spans,
            }],
        }]}

    def _snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)


def _otlp_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


# -- module-global recorder ----------------------------------------------------

def _env_enabled() -> bool:
    return os.environ.get("DTPU_TRACING", "1").strip().lower() not in (
        "0", "false", "no", "off")


_RECORDER = SpanRecorder(
    capacity=int(os.environ.get("DTPU_TRACE_CAPACITY", "8192") or 8192),
    enabled=_env_enabled())


def get_recorder() -> SpanRecorder:
    return _RECORDER


def set_enabled(flag: bool) -> None:
    _RECORDER.enabled = flag


class _NullSpan:
    """Shared no-op span: the disabled-recorder fast path allocates
    nothing (``span(...)`` returns this singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class span:
    """Record one span around a block. Usable as both ``with span(...)``
    and ``async with span(...)``.

    Parenting: an explicit request ``Context`` pins the span to that
    request's identity (span_id = ctx.span_id, parent = ctx.parent_span_id
    — the ids already propagated on wire frames), otherwise the ambient
    ``current_span`` contextvar parents it; with neither, a new root
    trace starts. While open, the span also publishes itself to
    ``current_trace`` so log lines carry trace_id/span_id.
    """

    __slots__ = ("_name", "_ctx", "_attrs", "_recorder", "_span",
                 "_tok_span", "_tok_trace")

    def __new__(cls, name: str, ctx=None, recorder: SpanRecorder | None = None,
                **attrs):
        rec = recorder if recorder is not None else _RECORDER
        if not rec.enabled:
            return NULL_SPAN
        self = object.__new__(cls)
        self._name = name
        self._ctx = ctx
        self._attrs = attrs or None
        self._recorder = rec
        self._span = None
        self._tok_span = None
        self._tok_trace = None
        return self

    def set(self, **attrs) -> None:
        """Attach attributes to the open span."""
        if self._span is not None:
            if self._span.attrs is None:
                self._span.attrs = {}
            self._span.attrs.update(attrs)

    # -- sync protocol --------------------------------------------------------
    def __enter__(self) -> "span":
        parent = current_span.get()
        if self._ctx is not None:
            trace_id = self._ctx.trace_id
            span_id = self._ctx.span_id
            parent_id = self._ctx.parent_span_id
            if parent is not None and parent.trace_id == trace_id:
                # Nested under an already-open local span of the same
                # trace (e.g. the worker.request span already carries
                # ctx.span_id): parent locally and mint a fresh id so
                # the child never collides with its parent.
                parent_id = parent.span_id
                span_id = generate_span_id()
        elif parent is not None:
            trace_id = parent.trace_id
            span_id = generate_span_id()
            parent_id = parent.span_id
        else:
            trace_id = generate_trace_id()
            span_id = generate_span_id()
            parent_id = None
        s = Span(trace_id=trace_id, span_id=span_id, parent_span_id=parent_id,
                 name=self._name, start_wall=time.time(),
                 start_mono=time.monotonic(), attrs=self._attrs)
        self._span = s
        self._tok_span = current_span.set(s)
        self._tok_trace = current_trace.set(
            {"trace_id": trace_id, "span_id": span_id})
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        s.end_mono = time.monotonic()
        if exc_type is not None:
            s.status = ("cancelled"
                        if issubclass(exc_type, asyncio.CancelledError)
                        else "error")
            if s.status == "error":
                if s.attrs is None:
                    s.attrs = {}
                s.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        for var, tok in ((current_span, self._tok_span),
                         (current_trace, self._tok_trace)):
            try:
                var.reset(tok)
            except ValueError:
                # Token from another context (generator finalized
                # elsewhere): drop the reset rather than crash cleanup.
                pass
        self._recorder.record(s)
        return False

    # -- async protocol -------------------------------------------------------
    async def __aenter__(self) -> "span":
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        return self.__exit__(exc_type, exc, tb)


# -- per-phase latency histograms ----------------------------------------------

_LATENCY_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                    1.0, 2.5, 5.0, 10.0, 30.0)
_BYTES_BUCKETS = (1 << 12, 1 << 16, 1 << 20, 4 << 20, 16 << 20, 64 << 20,
                  256 << 20, 1 << 30)


class PhaseMetrics:
    """The four phase histograms (+ transfer bytes) on a MetricsRegistry
    node. Every constructor touches its hierarchy-labeled child so the
    series appear in /metrics exposition before first traffic."""

    def __init__(self, registry):
        self.queue_wait = registry.histogram(
            "request_queue_wait_seconds",
            "Time a request waited for engine admission",
            buckets=_LATENCY_BUCKETS)
        self.prefill = registry.histogram(
            "prefill_step_seconds",
            "Prefill dispatch to first-token readback",
            buckets=_LATENCY_BUCKETS)
        self.decode = registry.histogram(
            "decode_step_seconds",
            "Decode window dispatch to host processing",
            buckets=_LATENCY_BUCKETS)
        self.kv_transfer = registry.histogram(
            "kv_transfer_seconds",
            "KV parcel transfer (send or recv) duration",
            ["direction"], buckets=_LATENCY_BUCKETS)
        self.kv_transfer_bytes = registry.histogram(
            "kv_transfer_bytes",
            "KV parcel transfer size in bytes",
            ["direction"], buckets=_BYTES_BUCKETS)
        for bound in (self.queue_wait, self.prefill, self.decode):
            bound.ensure()
        for direction in ("send", "recv"):
            self.kv_transfer.ensure(direction=direction)
            self.kv_transfer_bytes.ensure(direction=direction)


def phase_metrics(registry) -> PhaseMetrics:
    """Get-or-create the phase histograms for a registry node (cached on
    the ROOT registry per hierarchy position: node objects are ephemeral
    — ``namespace()``/``component()`` mint a new one per call — so
    repeated wiring of the same position stays idempotent)."""
    root = getattr(registry, "_root", registry)
    cache = getattr(root, "_dtpu_phase_metrics", None)
    if cache is None:
        cache = root._dtpu_phase_metrics = {}
    key = getattr(registry, "_hierarchy", None)
    cached = cache.get(key)
    if cached is None:
        cached = cache[key] = PhaseMetrics(registry)
    return cached


# -- debug endpoint payloads (shared by health.py and http_service.py) --------

def traces_index(recorder: SpanRecorder | None = None,
                 limit: int = 50) -> dict:
    rec = recorder or _RECORDER
    return {"enabled": rec.enabled, "capacity": rec.capacity,
            "dropped": rec.dropped, "traces": rec.recent(limit)}


def trace_payload(trace_id: str, fmt: str = "chrome",
                  recorder: SpanRecorder | None = None) -> dict | None:
    """Export one trace; None when the trace id is unknown."""
    rec = recorder or _RECORDER
    if not rec.trace(trace_id):
        return None
    if fmt == "chrome":
        return rec.export_chrome(trace_id)
    if fmt == "otlp":
        return rec.export_otlp(trace_id)
    if fmt == "spans":
        return {"trace_id": trace_id,
                "spans": [s.to_dict() for s in rec.trace(trace_id)]}
    raise ValueError(f"unknown trace format {fmt!r} "
                     "(expected chrome|otlp|spans)")


# -- on-demand profiler capture ------------------------------------------------

_profile_lock = threading.Lock()  # one capture at a time per process


async def capture_profile(duration_ms: int, out_dir: str,
                          recorder: SpanRecorder | None = None) -> dict:
    """Capture ``duration_ms`` of runtime activity into ``out_dir``.

    Preferred mode: a ``jax.profiler`` trace (TensorBoard/Perfetto
    loadable) covering device programs — one curl away from a TPU
    hot-path investigation. When JAX profiling is unavailable (CPU-only
    builds, profiler already claimed), degrades to dumping the span
    recorder's current contents as Chrome trace JSON so the capture is
    never empty-handed.
    """
    duration_ms = max(1, min(int(duration_ms), 60_000))
    os.makedirs(out_dir, exist_ok=True)
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a profile capture is already running")
    try:
        started = time.monotonic()
        mode = "jax"
        try:
            import jax

            jax.profiler.start_trace(out_dir)
            try:
                await asyncio.sleep(duration_ms / 1e3)
            finally:
                jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 — degrade, never fail
            log.warning("jax profiler capture unavailable (%s); "
                        "dumping span recorder instead", exc)
            mode = "spans"
            await asyncio.sleep(duration_ms / 1e3)
        rec = recorder or _RECORDER
        span_path = os.path.join(out_dir, "spans.chrome.json")

        # The ring buffer can hold tens of thousands of spans; serialize
        # and write off the loop — this endpoint runs DURING live serving.
        def _dump() -> None:
            with open(span_path, "w") as fh:
                json.dump(rec.export_chrome(), fh)

        await asyncio.to_thread(_dump)
        return {"mode": mode, "out_dir": out_dir,
                "span_dump": span_path,
                "duration_ms": duration_ms,
                "wall_s": round(time.monotonic() - started, 3)}
    finally:
        _profile_lock.release()
