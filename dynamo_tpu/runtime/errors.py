"""Shared runtime error types.

Shedding/transport errors carry a ``retryable`` class attribute — the
wire-level retryable/non-retryable split the overload defense maps to
HTTP: retryable capacity errors become 503 (+ Retry-After, try
elsewhere/later), non-retryable client-pacing rejections become 429
(the same request won't succeed without the client slowing down or
extending its deadline).
"""


class EngineError(RuntimeError):
    """Error raised by an engine/handler, propagated through response streams."""

    #: Whether retrying the same request (elsewhere or later) can succeed.
    retryable = False


class StreamIncompleteError(EngineError):
    """The response stream ended before generation completed (worker died or
    connection dropped mid-stream). The Migration operator retries on exactly
    this condition (reference lib/llm/src/migration.rs:26 — matches on
    'Stream ended before generation completed')."""

    retryable = True

    def __init__(self, message: str = "Stream ended before generation completed",
                 reason: str | None = None):
        super().__init__(message)
        #: Why the stream ended early, when the worker said so before
        #: dying — e.g. "role_flip" from a drain (llm/reconfig.py). The
        #: Migration operator copies it into the request context so the
        #: accounting ledger can attribute the migration cost.
        self.reason = reason


class NoInstancesError(EngineError):
    """No live instances are registered for the target endpoint."""

    retryable = True


class OverloadedError(EngineError):
    """Capacity rejection: all workers busy, admission queue full, or a
    projected-SLA gate fired (reference: router 503 busy_threshold path).
    Maps to HTTP 503 + Retry-After at the frontend so the client (or an
    upstream router) retries elsewhere/later; workers mark it on the
    wire with an 'overloaded: ' prefix so the class — and therefore the
    503/retry semantics — survive the request plane in distributed
    deployments."""

    WIRE_PREFIX = "overloaded: "
    retryable = True

    def __init__(self, message: str = "overloaded",
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RateLimitedError(EngineError):
    """Client-pacing rejection (deadline infeasible under the admission
    projection, deadline expired while queued, or batch traffic shed
    under brownout). Maps to HTTP 429 with ``error.type="rate_limited"``
    and Retry-After: unlike OverloadedError this is NOT retryable as-is —
    the same request with the same deadline/priority fails again until
    the client paces down. Wire-prefixed so the class survives the
    request plane."""

    WIRE_PREFIX = "rate_limited: "
    retryable = False

    def __init__(self, message: str = "rate limited",
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RoleTransitionError(EngineError):
    """A ``SetRole`` control verb was rejected by the worker's role state
    machine (llm/reconfig.py): stale/duplicate epoch (a reordered or
    replayed directive fenced out), an unknown role, or a flip already in
    flight. NOT retryable as-is — the caller must re-read the worker's
    role status and issue a fresh, higher-epoch directive. Wire-prefixed
    so the typed rejection survives the request plane (the planner or an
    operator may drive flips through a remote control path)."""

    WIRE_PREFIX = "role_transition: "
    retryable = False


class InvalidRequestError(EngineError):
    """The request itself is invalid (engine-level validation: unsupported
    sampling features, over-length prompts). Maps to HTTP 400 at the
    frontend; workers mark it on the wire with an 'invalid_request: '
    prefix so the class survives the request plane."""

    WIRE_PREFIX = "invalid_request: "


class AdapterNotFoundError(EngineError):
    """The request named a LoRA adapter this worker does not serve
    (engine/lora.py AdapterStore registry miss). Maps to HTTP 404 at the
    frontend — the OpenAI ``model`` field resolved to an adapter slug
    whose base worker no longer (or never) holds the adapter, which is
    a naming error, not a capacity condition. NOT retryable as-is: the
    same name keeps missing until an operator registers the adapter.
    Wire-prefixed so the 404 semantics survive the request plane."""

    WIRE_PREFIX = "adapter_not_found: "
    retryable = False
