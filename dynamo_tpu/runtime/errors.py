"""Shared runtime error types."""


class EngineError(RuntimeError):
    """Error raised by an engine/handler, propagated through response streams."""


class StreamIncompleteError(EngineError):
    """The response stream ended before generation completed (worker died or
    connection dropped mid-stream). The Migration operator retries on exactly
    this condition (reference lib/llm/src/migration.rs:26 — matches on
    'Stream ended before generation completed')."""

    def __init__(self, message: str = "Stream ended before generation completed"):
        super().__init__(message)


class NoInstancesError(EngineError):
    """No live instances are registered for the target endpoint."""


class OverloadedError(EngineError):
    """All workers busy (reference: router 503 busy_threshold path).
    Maps to HTTP 503 at the frontend so the router can retry elsewhere;
    workers mark it on the wire with an 'overloaded: ' prefix so the
    class — and therefore the 503/retry semantics — survive the request
    plane in distributed deployments."""

    WIRE_PREFIX = "overloaded: "


class InvalidRequestError(EngineError):
    """The request itself is invalid (engine-level validation: unsupported
    sampling features, over-length prompts). Maps to HTTP 400 at the
    frontend; workers mark it on the wire with an 'invalid_request: '
    prefix so the class survives the request plane."""

    WIRE_PREFIX = "invalid_request: "
