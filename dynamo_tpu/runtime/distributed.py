"""DistributedRuntime: the node-level singleton.

Capability parity with reference DistributedRuntime (lib/runtime/src/
distributed.rs:54-66): owns the control-plane client (coordinator = etcd+NATS),
the metrics registry root, and the component registry; supports a *static* mode
with no discovery (distributed.rs:178) used by single-process pipelines and
tests. Also hosts the system status server when enabled (SURVEY.md §5.5).
"""

from __future__ import annotations

import asyncio
import os
import random

from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.coordinator import Coordinator
from dynamo_tpu.runtime.coordinator_client import CoordinatorClient
from dynamo_tpu.runtime.logging import get_logger, init_logging
from dynamo_tpu.runtime.metrics import MetricsRegistry

log = get_logger("distributed")


class DistributedRuntime:
    def __init__(self, config: RuntimeConfig):
        self.config = config
        self.coordinator_client: CoordinatorClient | None = None
        self._embedded_coordinator: Coordinator | None = None
        self.metrics = MetricsRegistry()
        self.shutdown_event = asyncio.Event()
        self.instance_id: int = random.getrandbits(63)

    @classmethod
    async def from_settings(cls, config: RuntimeConfig | None = None
                            ) -> "DistributedRuntime":
        """Connect to the coordinator (dynamic mode)."""
        init_logging()
        config = config or RuntimeConfig.from_settings()
        runtime = cls(config)
        host, port = config.coordinator_addr
        runtime.coordinator_client = await CoordinatorClient.connect(
            host, port, lease_ttl_s=config.lease_ttl_s)
        # Instance ids are the primary lease id, as in the reference where the
        # etcd lease id identifies the instance (component.rs:98).
        runtime.instance_id = runtime.coordinator_client.primary_lease_id or runtime.instance_id
        return runtime

    @classmethod
    async def detached(cls, config: RuntimeConfig | None = None
                       ) -> "DistributedRuntime":
        """Static mode: no control plane (reference
        from_settings_without_discovery, distributed.rs:178)."""
        init_logging()
        config = config or RuntimeConfig.from_settings()
        config.static_mode = True
        return cls(config)

    @classmethod
    async def with_embedded_coordinator(
            cls, config: RuntimeConfig | None = None) -> "DistributedRuntime":
        """Single-process deployments (dynamo-run equivalent): start an
        in-process coordinator, then connect to it."""
        init_logging()
        config = config or RuntimeConfig.from_settings()
        coord = Coordinator("127.0.0.1", 0)
        await coord.start()
        config.coordinator_url = coord.url
        runtime = await cls.from_settings(config)
        runtime._embedded_coordinator = coord
        return runtime

    @property
    def has_discovery(self) -> bool:
        return self.coordinator_client is not None

    def namespace(self, name: str | None = None) -> Namespace:
        return Namespace(self, name or self.config.namespace)

    def require_coordinator(self) -> CoordinatorClient:
        if self.coordinator_client is None:
            raise RuntimeError("runtime is in static mode (no control plane)")
        return self.coordinator_client

    def shutdown(self) -> None:
        self.shutdown_event.set()

    async def wait_for_shutdown(self) -> None:
        # Workers block here until a signal handler or admin call sets
        # shutdown.
        # dtpu: ignore[unbounded-wait] -- serve-forever by contract
        await self.shutdown_event.wait()

    async def close(self) -> None:
        self.shutdown()
        if self.coordinator_client is not None:
            await self.coordinator_client.close()
            self.coordinator_client = None
        if self._embedded_coordinator is not None:
            await self._embedded_coordinator.stop()
            self._embedded_coordinator = None

    @property
    def advertise_host(self) -> str:
        return self.config.advertise_host or self.config.bind_host


def dynamo_worker():
    """Decorator: ``@dynamo_worker()`` wraps ``async def main(runtime)`` into a
    runnable entrypoint with runtime construction + signal handling (reference
    Python binding @dynamo_worker, SURVEY.md call stack 3.2)."""

    def wrap(fn):
        def entry() -> None:
            async def run() -> None:
                runtime = await DistributedRuntime.from_settings()
                import signal

                loop = asyncio.get_running_loop()
                for sig in (signal.SIGINT, signal.SIGTERM):
                    try:
                        loop.add_signal_handler(sig, runtime.shutdown)
                    except NotImplementedError:  # non-main thread
                        pass
                try:
                    await fn(runtime)
                finally:
                    await runtime.close()

            asyncio.run(run())

        entry.__name__ = fn.__name__
        entry.inner = fn
        return entry

    return wrap
