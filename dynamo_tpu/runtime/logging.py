"""Structured logging with distributed trace-context propagation.

Capability parity with reference lib/runtime/src/logging.rs: env-filtered levels
(DTPU_LOG ~ DYN_LOG, logging.rs:73), optional JSONL output (logging.rs:12), and
W3C trace-context trace_id/span_id generation + traceparent parse/inject
(logging.rs:111-175) so a request can be traced frontend -> worker.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import secrets
import sys
import time

_configured = False

# Per-task trace context (propagated through request headers / frames).
current_trace: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "dtpu_trace", default=None
)


def generate_trace_id() -> str:
    """128-bit lowercase hex trace id (W3C trace-context; logging.rs:111)."""
    return secrets.token_hex(16)


def generate_span_id() -> str:
    """64-bit lowercase hex span id (logging.rs:119)."""
    return secrets.token_hex(8)


def _is_lower_hex(s: str) -> bool:
    return bool(s) and all(c in "0123456789abcdef" for c in s)


def parse_traceparent(header: str) -> dict | None:
    """Parse a W3C ``traceparent`` header (logging.rs:127-175).

    Per the W3C trace-context spec, ids are lowercase hex, the all-zero
    trace-id/parent-id are invalid, and version ``ff`` is forbidden;
    malformed headers return None (caller starts a fresh trace)."""
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, parent_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(parent_id) != 16 \
            or len(flags) != 2:
        return None
    if not all(_is_lower_hex(p) for p in (version, trace_id, parent_id,
                                          flags)):
        return None
    if version == "ff":
        return None
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return {"trace_id": trace_id, "parent_id": parent_id, "flags": flags,
            "version": version}


def make_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.time(),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        trace = current_trace.get()
        if trace:
            out["trace_id"] = trace.get("trace_id")
            out["span_id"] = trace.get("span_id")
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        trace = current_trace.get()
        tid = f" trace={trace['trace_id'][:8]}" if trace else ""
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        return (f"{ts}.{int(record.msecs):03d} {record.levelname:<5} "
                f"{record.name}{tid}: {record.getMessage()}"
                + (f"\n{self.formatException(record.exc_info)}" if record.exc_info else ""))


def init_logging(level: str | None = None, jsonl: bool | None = None) -> None:
    """Idempotent logging init. DTPU_LOG sets the level filter; DTPU_LOG_JSONL=1
    switches to JSONL (reference logging.rs:8-16)."""
    global _configured
    if _configured:
        return
    _configured = True
    level = level or os.environ.get("DTPU_LOG", "info")
    jsonl = jsonl if jsonl is not None else (
        os.environ.get("DTPU_LOG_JSONL", "0").lower() in ("1", "true"))
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_JsonFormatter() if jsonl else _TextFormatter())
    root = logging.getLogger("dynamo_tpu")
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(f"dynamo_tpu.{name}")
