"""Deterministic seeded fault injection (the chaos plane).

The reference treats fault tolerance as a *tested* capability
(tests/fault_tolerance/ kills workers mid-stream and asserts streams
complete through the RetryManager). This module makes the failure space
systematically explorable in-process: a ``FaultPlan`` parsed from a
compact spec is threaded through the real I/O choke points — frame
read/write (drop, truncate, delay, duplicate, connection reset), the
coordinator lease keepalive (starvation → forced expiry), the request
plane (mid-stream disconnect), KV-plane pulls (error frames, partial
parcels, stalls) and coordinator queue pops — and every decision is
drawn from per-rule seeded RNG streams, so a scenario reproduces the
same fault sequence for the same seed.

Spec grammar (directives joined by ``;``)::

    DTPU_CHAOS="seed=7;frame.drop=0.02;frame.delay_ms=5..40:0.1;
                conn.reset=0.01;lease.starve@t=3;kv.pull_error=0.05"

    seed=N                 RNG seed for every rule stream (default 0)
    key=P                  fire with probability P per opportunity
    key=LO..HI[:P]         ranged magnitude (uniform in [LO,HI]) with
                           probability P (default 1.0) — e.g. delay ms
    key=xK                 deterministic: fire on the first K
                           opportunities, then never again
    key@t=T                one-shot: fire once at the first opportunity
                           at or after T seconds from arm()
    key@t=LO..HI           window: fire on EVERY opportunity while
                           LO <= t < HI seconds from arm()
    key@SITE=...           scope to one injection site (``service``,
                           ``client``, ``coord``, ``coord_client``,
                           ``kv``); unscoped rules match every site

Known keys (each hook site names the key it consults):

    frame.drop       write_frame: silently discard the frame
    frame.delay_ms   read/write_frame: sleep the drawn magnitude (ms)
    frame.dup        write_frame: send the frame twice
    frame.trunc      write_frame: send a byte-truncated frame, then
                     abort the connection (framing is unrecoverable)
    conn.reset       write_frame: abort the transport mid-operation
    stream.disconnect  request-plane client: sever the instance
                     connection upon receiving a data frame
    lease.starve     keepalive loop: skip keepalives long enough for
                     server-side lease expiry
    kv.pull_error    KV-plane server: answer a pull with an error frame
    kv.stall_ms      KV-plane server: sleep before sending the parcel
    kv.partial       KV-plane server: send a partial parcel, then drop
                     the connection
    queue.pop_error  coordinator client: fail queue_pop with
                     ConnectionError
    engine.stall_ms  engine loop: freeze the engine thread for the
                     drawn magnitude (ms) before dispatching — produces
                     a genuine decode_stall_seconds gap, so flight-
                     recorder anomaly capture is chaos-testable

Disabled (``DTPU_CHAOS`` unset / ``uninstall()``), every hook site is
guarded by the module-level ``ACTIVE`` bool — a single attribute read
and branch, no allocation, no behavior change.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import random
import re
import threading
import time

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("chaos")

#: Journal throttle: at most one chaos_inject event per (key, site) per
#: this many seconds (a 100%-probability delay rule fires per frame —
#: the decision plane wants "chaos is injecting X here", not a flood).
_JOURNAL_INTERVAL_S = 1.0

ENV_VAR = "DTPU_CHAOS"

# Fast gate consulted by every hook site: `if chaos.ACTIVE: ...`.
ACTIVE = False
_plan: "FaultPlan | None" = None

_RANGE_RE = re.compile(r"^(-?[\d.]+)\.\.(-?[\d.]+)(?::([\d.]+))?$")

# Injection-site names (for spec validation error messages only).
KNOWN_SITES = ("service", "client", "coord", "coord_client", "kv", "engine")


class FaultRule:
    """One parsed directive. Decisions consume this rule's own seeded
    RNG stream, so per-rule fault sequences are reproducible regardless
    of what other rules are doing."""

    __slots__ = ("key", "site", "prob", "lo", "hi", "times", "at_lo",
                 "at_hi", "_fired_once", "_fired_count", "_rng")

    def __init__(self, key: str, site: str | None, spec: str):
        self.key = key
        self.site = site
        self.prob: float | None = None
        self.lo: float | None = None
        self.hi: float | None = None
        self.times: int | None = None
        self.at_lo: float | None = None
        self.at_hi: float | None = None
        self._fired_once = False
        self._fired_count = 0
        self._rng: random.Random | None = None
        self._parse_value(spec)

    def _parse_value(self, text: str) -> None:
        text = text.strip()
        if self.site == "t":
            # key@t=T (one-shot) or key@t=LO..HI (window): the "site"
            # slot carried the time form; the rule itself is unscoped.
            self.site = None
            if ".." in text:
                lo, hi = text.split("..", 1)
                self.at_lo, self.at_hi = float(lo), float(hi)
            else:
                self.at_lo = float(text)
            return
        if text.startswith("x"):
            self.times = int(text[1:])
            return
        m = _RANGE_RE.match(text)
        if m:
            self.lo, self.hi = float(m.group(1)), float(m.group(2))
            self.prob = float(m.group(3)) if m.group(3) else 1.0
            return
        self.prob = float(text)
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(
                f"chaos probability out of range for {self.key}: {text}")

    def arm(self, seed: int) -> None:
        # Seed with a STRING: random.Random hashes str via SHA-512,
        # deterministic across processes (tuples would go through
        # PYTHONHASHSEED-randomized hash()).
        self._rng = random.Random(f"{seed}:{self.key}@{self.site or '*'}")
        self._fired_once = False
        self._fired_count = 0

    def draw(self, elapsed: float) -> float | None:
        """None = no fault this opportunity; a float = fire, with the
        drawn magnitude (1.0 for rules without a range)."""
        if self.at_lo is not None:
            if self.at_hi is None:
                if elapsed < self.at_lo or self._fired_once:
                    return None
                self._fired_once = True
                return 1.0
            if not (self.at_lo <= elapsed < self.at_hi):
                return None
            return 1.0
        if self.times is not None:
            if self._fired_count >= self.times:
                return None
            self._fired_count += 1
            return 1.0
        assert self._rng is not None, "rule not armed"
        if self._rng.random() >= (self.prob if self.prob is not None else 0):
            return None
        if self.lo is not None and self.hi is not None:
            return self._rng.uniform(self.lo, self.hi)
        return 1.0


class FaultPlan:
    """A parsed, armable set of fault rules."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self.rules: list[FaultRule] = []
        self._t0: float | None = None
        self._lock = threading.Lock()  # hooks fire from loop AND threads
        # Bounded decision log: (key, site, magnitude) per FIRED fault —
        # lets tests assert same-seed runs produce identical sequences.
        self.log: list[tuple[str, str, float]] = []
        # (key, site) -> [last_journal_t, suppressed_count] for the
        # journal emit throttle (chaos runs are self-documenting on the
        # decision plane without flooding the ring).
        self._journal_last: dict[tuple[str, str], list] = {}
        for directive in spec.split(";"):
            directive = directive.strip()
            if not directive:
                continue
            if "=" not in directive:
                raise ValueError(f"chaos directive missing '=': {directive!r}")
            head, _, value = directive.partition("=")
            head = head.strip()
            if head == "seed":
                self.seed = int(value)
                continue
            if "@" in head:
                key, _, site = head.partition("@")
            else:
                key, site = head, None
            self.rules.append(FaultRule(key.strip(), site, value))

    def arm(self) -> None:
        self._t0 = time.monotonic()
        for rule in self.rules:
            rule.arm(self.seed)

    def draw(self, key: str, site: str | None = None) -> float | None:
        """Consult every rule matching (key, site); first fire wins."""
        if self._t0 is None:
            self.arm()
        elapsed = time.monotonic() - self._t0
        with self._lock:
            for rule in self.rules:
                if rule.key != key:
                    continue
                if rule.site is not None and rule.site != site:
                    continue
                magnitude = rule.draw(elapsed)
                if magnitude is not None:
                    if len(self.log) < 4096:
                        self.log.append((key, site or "", magnitude))
                    self._journal_fire(key, site or "", magnitude)
                    return magnitude
        return None

    def _journal_fire(self, key: str, site: str, magnitude: float) -> None:
        """Every injected fault lands on the decision plane (throttled
        per key/site): a chaos run documents itself, and downstream
        breaker/shed/alert events can name the injection as their
        cause. Called under self._lock; the journal's own lock nests
        inside it and never takes this one back."""
        now = time.monotonic()
        state = self._journal_last.setdefault((key, site), [-1e18, 0])
        if now - state[0] < _JOURNAL_INTERVAL_S:
            state[1] += 1
            return
        suppressed, state[0], state[1] = state[1], now, 0
        try:
            journal.emit(EventKind.CHAOS_INJECT, key=key, site=site,
                         magnitude=round(magnitude, 4), seed=self.seed,
                         suppressed=suppressed)
        except Exception:  # noqa: BLE001 — fault injection must not crash
            log.exception("chaos journal emit failed")


# -- module-level install/uninstall -------------------------------------------

def install(plan: FaultPlan) -> FaultPlan:
    global _plan, ACTIVE
    plan.arm()
    _plan = plan
    ACTIVE = True
    log.warning("chaos plan armed (seed=%d): %s", plan.seed, plan.spec)
    return plan


def uninstall() -> None:
    global _plan, ACTIVE
    ACTIVE = False
    _plan = None


def plan() -> FaultPlan | None:
    return _plan


@contextlib.contextmanager
def active(spec: str):
    """Test helper: arm a plan for the duration of a block."""
    p = install(FaultPlan(spec))
    try:
        yield p
    finally:
        uninstall()


def install_from_env() -> None:
    spec = os.environ.get(ENV_VAR)
    if spec:
        install(FaultPlan(spec))


# -- hook helpers (call sites guard with `if chaos.ACTIVE:`) -------------------

def fire(key: str, site: str | None = None) -> bool:
    p = _plan
    return p is not None and p.draw(key, site) is not None


def value(key: str, site: str | None = None) -> float | None:
    p = _plan
    return None if p is None else p.draw(key, site)


async def on_frame_write(writer: asyncio.StreamWriter, data: bytes,
                         site: str | None) -> bytes | None:
    """Mutate one outgoing frame. Returns the bytes to write (possibly
    duplicated), or None to drop the frame entirely. Raises
    ConnectionResetError after aborting the transport for reset/truncate
    faults — the caller experiences exactly what a mid-write network
    failure looks like."""
    p = _plan
    if p is None:
        return data
    delay = p.draw("frame.delay_ms", site)
    if delay is not None:
        await asyncio.sleep(delay / 1000.0)
    if p.draw("conn.reset", site) is not None:
        _abort(writer)
        raise ConnectionResetError(f"chaos: injected connection reset ({site})")
    if p.draw("frame.trunc", site) is not None:
        # A truncated frame poisons the length-prefixed stream; the only
        # honest simulation is partial bytes followed by connection death.
        writer.write(data[:max(1, len(data) // 2)])
        _abort(writer)
        raise ConnectionResetError(f"chaos: injected truncated frame ({site})")
    if p.draw("frame.drop", site) is not None:
        return None
    if p.draw("frame.dup", site) is not None:
        return data + data
    return data


async def on_frame_read(site: str | None) -> None:
    """Inject receive-side latency before blocking on the next frame."""
    p = _plan
    if p is None:
        return
    delay = p.draw("frame.delay_ms", site)
    if delay is not None:
        await asyncio.sleep(delay / 1000.0)


def _abort(writer: asyncio.StreamWriter) -> None:
    transport = getattr(writer, "transport", None)
    if transport is not None:
        transport.abort()
    else:  # pragma: no cover - StreamWriter always has a transport
        writer.close()


# Arm directly from the environment at import: the hooks below this
# gate are compiled into the I/O paths of every process, so exporting
# DTPU_CHAOS is all a scenario needs — no code changes, no flags.
install_from_env()
