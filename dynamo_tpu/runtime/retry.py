"""Unified retry policy: jittered exponential backoff + retry budgets.

Every recovery path in the runtime used to carry its own ad-hoc sleep
constants (coordinator reconnect: 0.25*1.5^n capped at 5; prefill-queue
pop: flat 0.5; KV pulls: no retry at all). This module is the single
source of those decisions, reference-style (the Rust side leans on
tokio-retry semantics): a ``RetryPolicy`` describes the curve, a
``Backoff`` walks it for one operation, and a shared ``RetryBudget``
(token bucket) keeps a fleet of callers from synchronizing into a
retry storm when a dependency dies — once the budget drains, retries
still happen but only at the policy's max delay.

Usage::

    backoff = Backoff(policies.QUEUE_POP)
    while True:
        try:
            return await op()
        except ConnectionError:
            if not await backoff.sleep():
                raise   # attempts exhausted

``Backoff.reset()`` after a success re-arms the curve for long-lived
loops (the prefill-queue pop loop, the coordinator redial loop).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """An exponential-backoff curve with full-range jitter."""

    initial_delay_s: float = 0.25
    max_delay_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.1  # +/- fraction applied to each delay
    max_attempts: int | None = None  # None = retry forever

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        base = min(self.max_delay_s,
                   self.initial_delay_s * self.multiplier ** attempt)
        if self.jitter:
            r = (rng or random).random()
            base *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return max(0.0, base)


class RetryBudget:
    """Token bucket bounding how fast a caller may retry. Each retry
    spends one token; tokens refill at ``rate`` per second up to
    ``burst``. An empty budget doesn't forbid the retry — it forces it
    to the policy's max delay, which is what breaks a synchronized
    retry storm without killing liveness."""

    def __init__(self, rate: float = 2.0, burst: float = 10.0):
        self.rate = rate
        self.burst = burst
        self._tokens = burst
        self._t = time.monotonic()

    def try_spend(self, cost: float = 1.0) -> bool:
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False


class Backoff:
    """Stateful per-operation walk of a RetryPolicy."""

    def __init__(self, policy: RetryPolicy,
                 budget: RetryBudget | None = None,
                 rng: random.Random | None = None):
        self.policy = policy
        self.budget = budget
        self.attempt = 0
        self._rng = rng

    def next_delay(self) -> float | None:
        """The next sleep, or None when attempts are exhausted. An empty
        retry budget escalates the delay to the policy max instead of
        giving up (budget = pacing, max_attempts = termination)."""
        p = self.policy
        if p.max_attempts is not None and self.attempt >= p.max_attempts:
            return None
        d = p.delay(self.attempt, self._rng)
        self.attempt += 1
        if self.budget is not None and not self.budget.try_spend():
            d = max(d, p.max_delay_s)
        return d

    async def sleep(self) -> bool:
        """Async: back off once. False when attempts are exhausted."""
        d = self.next_delay()
        if d is None:
            return False
        await asyncio.sleep(d)
        return True

    def sleep_sync(self) -> bool:
        """Sync flavor for executor/engine threads (KV-plane pulls)."""
        d = self.next_delay()
        if d is None:
            return False
        time.sleep(d)
        return True

    def reset(self) -> None:
        self.attempt = 0


class policies:
    """The repo's named retry policies — the one place delay constants
    live. Callers reference these instead of inlining numbers."""

    # First dial to a coordinator that may still be starting up.
    COORD_CONNECT = RetryPolicy(initial_delay_s=0.25, max_delay_s=2.0,
                                multiplier=1.5, jitter=0.1, max_attempts=40)
    # Redial after a coordinator crash/restart: forever, capped.
    COORD_RECONNECT = RetryPolicy(initial_delay_s=0.25, max_delay_s=5.0,
                                  multiplier=1.5, jitter=0.2)
    # Prefill-queue pop loop survival (worker must keep draining).
    QUEUE_POP = RetryPolicy(initial_delay_s=0.25, max_delay_s=5.0,
                            multiplier=2.0, jitter=0.2)
    # KV-plane parcel pulls: bounded — past a few attempts the caller
    # prefills locally, which is always the cheap safe fallback.
    KV_PULL = RetryPolicy(initial_delay_s=0.05, max_delay_s=1.0,
                          multiplier=2.0, jitter=0.2, max_attempts=3)
    # Request-plane migration retries: near-immediate (the stream is
    # user-visible latency) but jittered so a worker death doesn't make
    # every migrated stream redial in lockstep.
    MIGRATION = RetryPolicy(initial_delay_s=0.05, max_delay_s=1.0,
                            multiplier=2.0, jitter=0.5)
    # Kubernetes scale patches (planner/kube.py): bounded — a planner
    # step that can't reach the API server journals a typed
    # planner_decision failure and lets the next interval retry, rather
    # than wedging the loop behind an endless redial.
    KUBE_SCALE = RetryPolicy(initial_delay_s=0.5, max_delay_s=4.0,
                             multiplier=2.0, jitter=0.2, max_attempts=3)
    # G4 peer-tier breaker curve (kv_plane.RemoteBlockSource): the
    # cooldown after the Nth consecutive failure on one peer. Not a
    # sleep — the consult runs on the engine thread — but the open
    # duration of that peer's breaker; the post-cooldown consult is the
    # half-open probe, and one success resets the curve.
    G4_PEER_BREAKER = RetryPolicy(initial_delay_s=5.0, max_delay_s=120.0,
                                  multiplier=2.0, jitter=0.0)
