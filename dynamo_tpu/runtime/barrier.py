"""Leader/worker barrier over the coordinator KV.

Capability parity with reference LeaderBarrier/WorkerBarrier
(lib/runtime/src/utils/leader_worker_barrier.rs:137,230): the leader publishes
data under ``{root}/leader`` and waits for N workers to check in under
``{root}/workers/{id}``; workers post their data and wait for the leader's.
Used to bootstrap multi-host engine groups and KVBM leader/worker pairs.
"""

from __future__ import annotations

import asyncio
from typing import Any

from dynamo_tpu.runtime.coordinator_client import CoordinatorClient

BARRIER_ROOT = "barriers/"


class LeaderBarrier:
    def __init__(self, client: CoordinatorClient, barrier_id: str, num_workers: int):
        self.client = client
        self.root = f"{BARRIER_ROOT}{barrier_id}/"
        self.num_workers = num_workers

    async def sync(self, data: Any, timeout: float = 60.0) -> dict[str, Any]:
        """Publish leader data; return {worker_id: worker_data} once all
        workers have checked in."""
        await self.client.kv_put(self.root + "leader", data, use_primary_lease=True)
        watch = await self.client.watch_prefix(self.root + "workers/")
        workers: dict[str, Any] = {
            e["k"].rsplit("/", 1)[-1]: e["v"] for e in watch.snapshot}
        try:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout
            while len(workers) < self.num_workers:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"barrier {self.root}: {len(workers)}/{self.num_workers} "
                        "workers after timeout")
                event = await asyncio.wait_for(watch.events.get(), remaining)
                if event["event"] == "put":
                    workers[event["key"].rsplit("/", 1)[-1]] = event["value"]
            return workers
        finally:
            await watch.cancel()


class WorkerBarrier:
    def __init__(self, client: CoordinatorClient, barrier_id: str, worker_id: str):
        self.client = client
        self.root = f"{BARRIER_ROOT}{barrier_id}/"
        self.worker_id = worker_id

    async def sync(self, data: Any, timeout: float = 60.0) -> Any:
        """Post worker data; return the leader's data once present."""
        watch = await self.client.watch_prefix(self.root + "leader")
        try:
            await self.client.kv_put(self.root + f"workers/{self.worker_id}",
                                     data, use_primary_lease=True)
            if watch.snapshot:
                return watch.snapshot[0]["v"]
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise TimeoutError(f"barrier {self.root}: no leader after timeout")
                event = await asyncio.wait_for(watch.events.get(), remaining)
                if event["event"] == "put":
                    return event["value"]
        finally:
            await watch.cancel()
