"""SLO plane: declarative targets, sliding-window SLIs, burn-rate alerts.

PR 1 built the observability *mechanics* (spans, phase histograms,
``/debug/traces``); this module answers the two questions a fleet
operator actually asks: "are we inside our SLOs right now, and how fast
are we burning error budget?" — and shapes the answer so the planner
and overload subsystems can consume it as a pressure signal.

Model (SRE-workbook style):

- A **target** names an SLI, a threshold, and an objective fraction,
  e.g. ``ttft``: 99% of requests reach their first token within
  ``ttft_p99_ms``. Each observed event is *good* or *bad* against the
  threshold; the SLI over a window is good/total.
- The **error budget** is ``1 - objective``. The **burn rate** over a
  window is ``bad_fraction / budget``: burn 1.0 spends exactly the
  budget over the SLO period; burn 14.4 exhausts 2% of a 30-day budget
  in one hour.
- **Multi-window alerts**: a ``fast`` page fires when BOTH the 5m and
  1h windows burn above ``fast_burn`` (default 14.4) — urgent and not
  a blip; a ``slow`` ticket fires when both the 6h and 3d windows burn
  above ``slow_burn`` (default 1.0) — slow leak that will exhaust the
  budget. Alerts clear when the short window of the pair recovers.

Determinism: the clock is injectable (``clock=``) and nothing sleeps —
the whole plane is driven by ``observe_*`` calls and evaluated lazily,
so tests walk a fake clock through hours in microseconds.

State is exported three ways: ``dynamo_tpu_slo_*`` gauges on the
metrics registry, the ``/debug/slo`` JSON payload (served by both the
frontend and the per-worker ``SystemStatusServer`` via
``runtime/health.py``), and ``pressure()`` — a compact level 0..3 the
planner/overload loops can poll without parsing alert structures.

Targets come from ``RuntimeConfig.slo`` (``[slo]`` TOML table,
``DTPU_SLO_*`` env). A threshold of 0 disables that target.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("slo")

# Burn-rate windows, seconds (SRE workbook multi-window pairs).
WINDOW_FAST_SHORT = 5 * 60
WINDOW_FAST_LONG = 60 * 60
WINDOW_SLOW_SHORT = 6 * 3600
WINDOW_SLOW_LONG = 3 * 24 * 3600
WINDOWS = {
    "5m": WINDOW_FAST_SHORT,
    "1h": WINDOW_FAST_LONG,
    "6h": WINDOW_SLOW_SHORT,
    "3d": WINDOW_SLOW_LONG,
}


@dataclasses.dataclass
class SloConfig:
    """Declarative SLO targets + alert tuning. All plain scalars so the
    generic DTPU_SLO_<FIELD> env override in runtime/config.py maps 1:1
    (0 disables the individual target)."""

    enabled: bool = True

    # -- targets --------------------------------------------------------------
    # 99% of requests must reach their first token within this budget.
    ttft_p99_ms: float = 0.0
    # 99% of inter-token gaps must stay under this budget.
    itl_p99_ms: float = 0.0
    # Availability: at most this fraction of requests may fail (5xx /
    # internal errors; typed sheds count against goodput, not errors).
    error_rate: float = 0.0
    # Goodput: at least this fraction of all arrivals must complete OK
    # (sheds and failures are both bad events here).
    goodput: float = 0.0

    # -- alert tuning ---------------------------------------------------------
    # Burn-rate thresholds for the fast (5m & 1h) page and the slow
    # (6h & 3d) ticket.
    fast_burn: float = 14.4
    slow_burn: float = 1.0
    # Sliding-window bucket width; also the lazy re-evaluation cadence.
    bucket_s: float = 10.0
    # Minimum events in the short window before an alert may fire: a
    # single bad request on an idle fleet is not a page.
    min_events: int = 10

    # -- per-request accounting (tentpole b; consumed by llm/recorder.py) -----
    # Bounded in-memory ring of accounting records (/debug/requests).
    request_ring: int = 1024
    # Optional JSONL sink for accounting records ("" = in-memory only).
    request_log_path: str = ""

    def targets(self) -> dict[str, tuple[float, float]]:
        """Configured targets: name -> (threshold, objective). Latency
        thresholds are in seconds; rate targets use threshold 0 (the
        good/bad call is made by the caller)."""
        out: dict[str, tuple[float, float]] = {}
        if self.ttft_p99_ms > 0:
            out["ttft"] = (self.ttft_p99_ms / 1e3, 0.99)
        if self.itl_p99_ms > 0:
            out["itl"] = (self.itl_p99_ms / 1e3, 0.99)
        if self.error_rate > 0:
            out["availability"] = (0.0, 1.0 - self.error_rate)
        if self.goodput > 0:
            out["goodput"] = (0.0, self.goodput)
        return out


class _WindowedRatio:
    """Good/total counts in time buckets; windowed sums for SLI/burn."""

    __slots__ = ("_bucket_s", "_horizon_s", "_buckets", "_clock")

    def __init__(self, bucket_s: float, horizon_s: float,
                 clock: Callable[[], float]):
        self._bucket_s = bucket_s
        self._horizon_s = horizon_s
        self._clock = clock
        # deque of [bucket_index, good, total], oldest first.
        self._buckets: collections.deque[list] = collections.deque()

    def observe(self, good: bool) -> None:
        idx = int(self._clock() / self._bucket_s)
        b = self._buckets[-1] if self._buckets else None
        if b is None or b[0] != idx:
            self._prune(idx)
            b = [idx, 0, 0]
            self._buckets.append(b)
        if good:
            b[1] += 1
        b[2] += 1

    def _prune(self, now_idx: int) -> None:
        keep = int(self._horizon_s / self._bucket_s) + 1
        while self._buckets and self._buckets[0][0] < now_idx - keep:
            self._buckets.popleft()

    def window(self, seconds: float) -> tuple[int, int]:
        """(good, total) over the trailing ``seconds``."""
        lo = int((self._clock() - seconds) / self._bucket_s)
        good = total = 0
        for idx, g, t in reversed(self._buckets):
            if idx <= lo:
                break
            good += g
            total += t
        return good, total


@dataclasses.dataclass
class SloPressure:
    """Compact pressure signal for the planner/overload loops.

    level 0 = inside budget everywhere; 1 = some target burning faster
    than sustainable (burn > slow_burn on the fast-short window); 2 = a
    fast page is firing on one target; 3 = pages on several targets (or
    availability paging) — degrade hard / add capacity NOW.
    """

    level: int
    worst_burn: float
    failing: tuple[str, ...]

    def to_wire(self) -> dict:
        return {"level": self.level, "worst_burn": round(self.worst_burn, 3),
                "failing": list(self.failing)}


class SloPlane:
    """Sliding-window SLI computation + multi-window burn-rate alerts
    for the configured targets. Thread-safe: observations come from the
    event loop and (potentially) engine threads."""

    def __init__(self, config: SloConfig | None = None, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or SloConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self.targets = self.cfg.targets() if self.cfg.enabled else {}
        self._series = {
            name: _WindowedRatio(self.cfg.bucket_s, WINDOW_SLOW_LONG, clock)
            for name in self.targets}
        # target -> {"fast": bool, "slow": bool}
        self.alerts: dict[str, dict[str, bool]] = {
            name: {"fast": False, "slow": False} for name in self.targets}
        self.pages_total = 0  # fast-page rising edges (observability)
        # (target, severity) -> journal ref of the firing event, so the
        # clear names its own fire as the cause.
        self._alert_refs: dict[tuple[str, str], str] = {}
        self._last_eval = -1e18
        self._callbacks: list[Callable[[str, str], None]] = []
        self._m_sli = self._m_burn = self._m_alert = None
        if metrics is not None:
            m = metrics.namespace("slo")
            self._m_sli = m.gauge(
                "slo_sli", "Windowed SLI (good/total) per objective",
                ["objective", "window"])
            self._m_burn = m.gauge(
                "slo_burn_rate",
                "Error-budget burn rate per objective and window",
                ["objective", "window"])
            self._m_alert = m.gauge(
                "slo_alert_active",
                "1 while a burn-rate alert fires (severity=fast|slow)",
                ["objective", "severity"])
            for name in self.targets:
                for w in WINDOWS:
                    self._m_sli.ensure(objective=name, window=w)
                    self._m_burn.ensure(objective=name, window=w)
                for sev in ("fast", "slow"):
                    self._m_alert.ensure(objective=name, severity=sev)

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    def on_page(self, callback: Callable[[str, str], None]) -> None:
        """Register ``callback(target, severity)`` for alert rising
        edges — the flight recorder hooks this to freeze its ring."""
        self._callbacks.append(callback)

    # -- observations ---------------------------------------------------------
    def observe_ttft(self, seconds: float) -> None:
        self._observe_latency("ttft", seconds)

    def observe_itl(self, seconds: float) -> None:
        self._observe_latency("itl", seconds)

    def _observe_latency(self, name: str, seconds: float) -> None:
        series = self._series.get(name)
        if series is None:
            return
        threshold, _ = self.targets[name]
        with self._lock:
            series.observe(seconds <= threshold)
        self._maybe_evaluate()

    def observe_request(self, ok: bool, shed: bool = False) -> None:
        """One finished arrival. ``ok`` = completed successfully;
        ``shed`` = typed 429/503 rejection (bad for goodput, NOT an
        availability error — shedding is the defense working)."""
        with self._lock:
            avail = self._series.get("availability")
            if avail is not None:
                avail.observe(ok or shed)
            goodput = self._series.get("goodput")
            if goodput is not None:
                goodput.observe(ok)
        self._maybe_evaluate()

    # -- evaluation -----------------------------------------------------------
    def _maybe_evaluate(self) -> None:
        now = self._clock()
        if now - self._last_eval >= self.cfg.bucket_s:
            self.evaluate()

    def burn_rate(self, name: str, window_s: float) -> tuple[float, int]:
        """(burn, events) for one target over one window."""
        _, objective = self.targets[name]
        budget = max(1e-9, 1.0 - objective)
        good, total = self._series[name].window(window_s)
        if total == 0:
            return 0.0, 0
        return ((total - good) / total) / budget, total

    def evaluate(self) -> dict[str, dict[str, bool]]:
        """Recompute burn rates, update alert states + gauges, and fire
        page callbacks on rising edges. Returns the alert map."""
        self._last_eval = self._clock()
        cfg = self.cfg
        with self._lock:
            for name in self.targets:
                burns = {w: self.burn_rate(name, s)
                         for w, s in WINDOWS.items()}
                state = self.alerts[name]
                pairs = (("fast", "5m", "1h", cfg.fast_burn),
                         ("slow", "6h", "3d", cfg.slow_burn))
                for sev, short, long_, threshold in pairs:
                    b_short, n_short = burns[short]
                    b_long, _ = burns[long_]
                    if state[sev]:
                        # Clear when the short window recovers.
                        if b_short < threshold:
                            state[sev] = False
                            log.info("SLO %s %s-burn alert cleared", name,
                                     sev)
                            journal.emit(
                                EventKind.SLO_ALERT_CLEAR,
                                cause=self._alert_refs.pop((name, sev),
                                                           None),
                                objective=name, severity=sev,
                                burn_short=round(b_short, 3))
                    elif (b_short > threshold and b_long > threshold
                          and n_short >= cfg.min_events):
                        state[sev] = True
                        if sev == "fast":
                            self.pages_total += 1
                        log.warning(
                            "SLO %s %s-burn alert FIRING: burn %s=%.1f "
                            "%s=%.1f (threshold %.1f)", name, sev, short,
                            b_short, long_, b_long, threshold)
                        # Cause: the most recent defensive action on
                        # this process — the burn usually IS what the
                        # sheds/breakers/preempts were reacting to.
                        self._alert_refs[(name, sev)] = journal.emit(
                            EventKind.SLO_ALERT_FIRE,
                            cause=journal.recent_ref(
                                EventKind.SHED,
                                EventKind.BREAKER_TRANSITION,
                                EventKind.PREEMPT,
                                EventKind.BROWNOUT_CHANGE),
                            objective=name, severity=sev,
                            burn_short=round(b_short, 3),
                            burn_long=round(b_long, 3),
                            threshold=threshold, events=n_short)
                        for cb in list(self._callbacks):
                            try:
                                cb(name, sev)
                            except Exception:  # noqa: BLE001 — observers only
                                log.exception("SLO page callback failed")
                if self._m_burn is not None:
                    for w, (b, _) in burns.items():
                        self._m_burn.set(b, objective=name, window=w)
                        good, total = self._series[name].window(WINDOWS[w])
                        self._m_sli.set(good / total if total else 1.0,
                                        objective=name, window=w)
                    for sev in ("fast", "slow"):
                        self._m_alert.set(1.0 if state[sev] else 0.0,
                                          objective=name, severity=sev)
        return self.alerts

    def pressure(self) -> SloPressure:
        """Compact 0..3 signal (see SloPressure) for planner/overload."""
        self.evaluate()
        worst = 0.0
        failing: list[str] = []
        paging: list[str] = []
        for name in self.targets:
            burn, _ = self.burn_rate(name, WINDOW_FAST_SHORT)
            worst = max(worst, burn)
            if self.alerts[name]["fast"]:
                paging.append(name)
            elif burn > self.cfg.slow_burn or self.alerts[name]["slow"]:
                failing.append(name)
        if len(paging) >= 2 or "availability" in paging:
            level = 3
        elif paging:
            level = 2
        elif failing:
            level = 1
        else:
            level = 0
        return SloPressure(level, worst, tuple(paging + failing))

    # -- /debug/slo payload ---------------------------------------------------
    def snapshot(self) -> dict:
        self.evaluate()
        targets = {}
        for name, (threshold, objective) in self.targets.items():
            windows = {}
            for w, s in WINDOWS.items():
                good, total = self._series[name].window(s)
                burn, _ = self.burn_rate(name, s)
                windows[w] = {
                    "sli": round(good / total, 6) if total else None,
                    "events": total,
                    "burn": round(burn, 3),
                }
            targets[name] = {
                "threshold_s": threshold if threshold else None,
                "objective": objective,
                "windows": windows,
                "alerts": dict(self.alerts[name]),
            }
        return {
            "enabled": self.enabled,
            "fast_burn_threshold": self.cfg.fast_burn,
            "slow_burn_threshold": self.cfg.slow_burn,
            "pages_total": self.pages_total,
            "targets": targets,
            "pressure": self.pressure().to_wire(),
        }


# -- process-global plane ------------------------------------------------------
#
# Like tracing's module-global recorder: the debug routes (runtime/
# health.py) and the HTTP frontend feed/serve one process-wide plane.
# ``configure()`` is called by the entrypoints (frontend, launcher,
# worker) once the RuntimeConfig is known; before that the default
# plane has no targets and every observe is a cheap no-op.

_PLANE = SloPlane(SloConfig())


def configure(config: SloConfig, metrics=None,
              clock: Callable[[], float] = time.monotonic) -> SloPlane:
    global _PLANE
    _PLANE = SloPlane(config, metrics=metrics, clock=clock)
    if _PLANE.enabled:
        log.info("SLO plane armed: %s",
                 ", ".join(sorted(_PLANE.targets)))
    return _PLANE


def get_plane() -> SloPlane:
    return _PLANE
