"""Pluggable key-value storage behind one async surface.

Reference: ``lib/runtime/src/storage/key_value_store.rs:419`` defines a
``KeyValueStore`` trait with etcd, NATS-KV, and in-memory implementations so
components can run against whichever backend a deployment provides. The
TPU-native equivalent keys the trait off the coordinator client's KV surface
(``kv_put/kv_create/kv_get/kv_get_prefix/kv_delete/kv_delete_prefix/
watch_prefix``), so ``CoordinatorClient`` *is* one implementation already —
this module adds the other two:

- ``MemoryStore`` — in-process, zero dependencies; the static/single-process
  mode backend (reference ``key_value_store/mem.rs``).
- ``FileStore`` — a directory of JSON documents with cross-process polling
  watches; persistence without any server (fills the role of the reference's
  NATS-KV bucket for single-node deployments).

Consumers (``ModelWatcher``, disagg conf, planner state) take any object with
this surface, so discovery and config watching are storage-pluggable exactly
as in the reference.

Watch contract (matches ``coordinator_client.WatchStream``): the returned
stream has a ``snapshot`` list of ``{"k", "v"}`` items for keys present at
registration, then async-iterates ``{"event": "put"|"delete", "key",
"value"}`` events, and supports ``cancel()``.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
from typing import Any, AsyncIterator, Protocol, runtime_checkable


@runtime_checkable
class KeyValueStore(Protocol):
    """Structural trait for KV backends (reference key_value_store.rs:419).

    ``CoordinatorClient`` satisfies this natively; ``MemoryStore`` and
    ``FileStore`` below are the server-free implementations."""

    async def kv_put(self, key: str, value: Any, lease_id: int | None = None,
                     use_primary_lease: bool = False) -> int: ...
    async def kv_create(self, key: str, value: Any,
                        lease_id: int | None = None,
                        use_primary_lease: bool = False) -> bool: ...
    async def kv_get(self, key: str) -> Any | None: ...
    async def kv_get_prefix(self, prefix: str) -> list[dict]: ...
    async def kv_delete(self, key: str) -> bool: ...
    async def kv_delete_prefix(self, prefix: str) -> int: ...
    async def watch_prefix(self, prefix: str): ...


class LocalWatch:
    """Watch stream produced by the local stores.

    Mirrors the coordinator ``WatchStream`` shape (snapshot + event queue +
    cancel) so consumers can't tell the difference."""

    def __init__(self, snapshot: list[dict], prefix: str,
                 on_cancel=None):
        self.snapshot = snapshot
        self.prefix = prefix
        self.known_keys = {item["k"] for item in snapshot}
        # Watch deltas are lossless by contract; volume is bounded by
        # store churn, not request traffic.
        # dtpu: ignore[unbounded-queue] -- see above
        self.events: asyncio.Queue[dict] = asyncio.Queue()
        self._on_cancel = on_cancel
        self._cancelled = False

    def deliver(self, event: dict) -> None:
        if event["event"] == "put":
            self.known_keys.add(event["key"])
        else:
            self.known_keys.discard(event["key"])
        self.events.put_nowait(event)

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            yield await self.events.get()

    async def cancel(self) -> None:
        if self._cancelled:
            return
        self._cancelled = True
        if self._on_cancel is not None:
            self._on_cancel(self)


class MemoryStore:
    """In-process KV store with prefix watches (reference mem.rs).

    Lease arguments are accepted for surface compatibility and ignored —
    there is no liveness to track inside one process."""

    def __init__(self):
        self._data: dict[str, Any] = {}
        self._objects: dict[str, bytes] = {}
        self._rev = 0
        self._watches: list[LocalWatch] = []

    def _notify(self, event: str, key: str, value: Any) -> None:
        for w in self._watches:
            if key.startswith(w.prefix):
                w.deliver({"event": event, "key": key, "value": value})

    async def kv_put(self, key: str, value: Any, lease_id: int | None = None,
                     use_primary_lease: bool = False) -> int:
        self._rev += 1
        self._data[key] = value
        self._notify("put", key, value)
        return self._rev

    async def kv_create(self, key: str, value: Any,
                        lease_id: int | None = None,
                        use_primary_lease: bool = False) -> bool:
        if key in self._data:
            return False
        await self.kv_put(key, value)
        return True

    async def kv_get(self, key: str) -> Any | None:
        return self._data.get(key)

    async def kv_get_prefix(self, prefix: str) -> list[dict]:
        return [{"k": k, "v": v} for k, v in sorted(self._data.items())
                if k.startswith(prefix)]

    async def kv_delete(self, key: str) -> bool:
        if key not in self._data:
            return False
        self._data.pop(key)
        self._notify("delete", key, None)
        return True

    async def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._data if k.startswith(prefix)]
        for k in keys:
            await self.kv_delete(k)
        return len(keys)

    async def watch_prefix(self, prefix: str) -> LocalWatch:
        watch = LocalWatch(await self.kv_get_prefix(prefix), prefix,
                           on_cancel=self._watches.remove)
        self._watches.append(watch)
        return watch

    # Object store (reference NATS object store, nats.rs:174) — carries
    # tokenizer artifacts so model cards resolve against this store too.
    async def object_put(self, key: str, data: bytes) -> None:
        self._objects[key] = bytes(data)

    async def object_get(self, key: str) -> bytes | None:
        return self._objects.get(key)


def _encode_key(key: str) -> str:
    return base64.urlsafe_b64encode(key.encode()).decode() + ".json"


def _decode_key(name: str) -> str | None:
    if not name.endswith(".json"):
        return None
    try:
        return base64.urlsafe_b64decode(name[:-5].encode()).decode()
    except (ValueError, UnicodeDecodeError):
        return None


class FileStore:
    """KV store over a directory of JSON documents.

    Cross-process capable: every mutation is an atomic rename, revisions
    come from a lock-protected counter file, and watches poll the directory
    (``poll_interval``) diffing per-key revisions — put and delete events
    are synthesized from the diff, so two processes sharing the directory
    see each other's changes without a server."""

    def __init__(self, root: str, poll_interval: float = 0.05):
        self.root = root
        self.poll_interval = poll_interval
        os.makedirs(root, exist_ok=True)
        self._watches: list[LocalWatch] = []
        self._poll_task: asyncio.Task | None = None

    # -- revision counter (flock-protected, shared across processes) --------
    def _with_rev_lock(self, fn):
        """Run ``fn(next_rev)`` while holding the cross-process revision
        lock. Mutations happen inside the lock so revision order and file
        order can't diverge (two same-key writers racing os.replace would
        otherwise let the older revision land last and win)."""
        import fcntl
        path = os.path.join(self.root, "_rev")
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            raw = os.read(fd, 64)
            rev = int(raw) + 1 if raw else 1
            os.lseek(fd, 0, os.SEEK_SET)
            os.ftruncate(fd, 0)
            os.write(fd, str(rev).encode())
            fn(rev)
            return rev
        finally:
            os.close(fd)  # releases the flock

    def _path(self, key: str) -> str:
        return os.path.join(self.root, _encode_key(key))

    def _read(self, path: str) -> dict | None:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (FileNotFoundError, json.JSONDecodeError):
            # JSONDecodeError: racing a concurrent atomic rename is not
            # possible (rename is atomic), but a torn manual edit is.
            return None

    def _scan(self, prefix: str) -> dict[str, dict]:
        out = {}
        for name in os.listdir(self.root):
            key = _decode_key(name)
            if key is None or not key.startswith(prefix):
                continue
            doc = self._read(os.path.join(self.root, name))
            if doc is not None and "rev" in doc and "v" in doc:
                out[key] = doc
        return out

    async def kv_put(self, key: str, value: Any, lease_id: int | None = None,
                     use_primary_lease: bool = False) -> int:
        def write(rev: int) -> None:
            doc = {"k": key, "v": value, "rev": rev}
            tmp = self._path(key) + f".tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self._path(key))
        return self._with_rev_lock(write)

    async def kv_create(self, key: str, value: Any,
                        lease_id: int | None = None,
                        use_primary_lease: bool = False) -> bool:
        # Reservation and content are ONE atomic step: the full document
        # is written to a tmp file and link()ed into place (fails if the
        # key exists). A crash can no longer leave an empty reserved file
        # that wedges the key (kv_create False forever, kv_get absent).
        created = False

        def write(rev: int) -> None:
            nonlocal created
            doc = {"k": key, "v": value, "rev": rev}
            tmp = self._path(key) + f".tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            try:
                os.link(tmp, self._path(key))
                created = True
            except FileExistsError:
                created = False
            finally:
                os.unlink(tmp)

        self._with_rev_lock(write)
        return created

    async def kv_get(self, key: str) -> Any | None:
        # The root may sit on NFS (same rationale as object_get): every
        # doc read goes through a worker thread, not the event loop.
        doc = await asyncio.to_thread(self._read, self._path(key))
        return None if doc is None else doc["v"]

    async def kv_get_prefix(self, prefix: str) -> list[dict]:
        docs = await asyncio.to_thread(self._scan, prefix)
        return [{"k": k, "v": d["v"]} for k, d in sorted(docs.items())]

    async def kv_delete(self, key: str) -> bool:
        try:
            os.remove(self._path(key))
            return True
        except FileNotFoundError:
            return False

    async def kv_delete_prefix(self, prefix: str) -> int:
        n = 0
        for key in list(await asyncio.to_thread(self._scan, prefix)):
            n += await self.kv_delete(key)
        return n

    async def watch_prefix(self, prefix: str) -> LocalWatch:
        docs = await asyncio.to_thread(self._scan, prefix)
        watch = LocalWatch([{"k": k, "v": d["v"]}
                            for k, d in sorted(docs.items())], prefix,
                           on_cancel=self._drop_watch)
        watch._seen = {k: d["rev"] for k, d in docs.items()}  # per-key revs
        self._watches.append(watch)
        if self._poll_task is None or self._poll_task.done():
            self._poll_task = asyncio.create_task(self._poll_loop())
        return watch

    async def object_put(self, key: str, data: bytes) -> None:
        # Blobs can be large (KV snapshots, model cards) and the root may
        # sit on NFS: keep the write off the event loop.
        def _write() -> None:
            obj_dir = os.path.join(self.root, "objects")
            os.makedirs(obj_dir, exist_ok=True)
            path = os.path.join(obj_dir, _encode_key(key))
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)

        await asyncio.to_thread(_write)

    async def object_get(self, key: str) -> bytes | None:
        def _read() -> bytes | None:
            try:
                with open(os.path.join(self.root, "objects",
                                       _encode_key(key)), "rb") as fh:
                    return fh.read()
            except FileNotFoundError:
                return None

        return await asyncio.to_thread(_read)

    def _drop_watch(self, watch: LocalWatch) -> None:
        self._watches.remove(watch)
        if not self._watches and self._poll_task is not None:
            self._poll_task.cancel()
            self._poll_task = None

    async def _poll_loop(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval)
            for w in self._watches:
                try:
                    docs = await asyncio.to_thread(self._scan, w.prefix)
                    seen = w._seen
                    for k, d in docs.items():
                        if seen.get(k) != d["rev"]:
                            w.deliver({"event": "put", "key": k,
                                       "value": d["v"]})
                    for k in list(seen):
                        if k not in docs:
                            w.deliver({"event": "delete", "key": k,
                                       "value": None})
                    w._seen = {k: d["rev"] for k, d in docs.items()}
                except OSError:
                    # Transient filesystem trouble (NFS hiccup, dir
                    # recreated): skip this tick, keep the watch alive.
                    continue
