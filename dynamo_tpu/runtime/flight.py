"""Engine flight recorder: a fixed-slot ring of per-window engine state,
frozen into a diagnostic bundle when an anomaly fires.

"What exactly was the engine doing when latency spiked five minutes
ago?" — the span recorder answers per-request, but the *engine-level*
picture (batch occupancy, free KV pages, chunk tokens in flight,
preemptions, brownout level, window pacing) lives only in transient
loop state. This module records one compact row per engine window into
preallocated numpy columns — no Python objects are created or retained
on the hot path, and idle-stable windows (nothing active, nothing
changed) are skipped entirely, so the steady-state cost is a few array
stores (asserted allocation-free in tests/test_slo.py in the style of
``test_disabled_recorder_zero_allocations``).

Anomaly capture: an SLO fast-burn page (runtime/slo.py ``on_page``) or
a decode-stall tail spike (engine/engine.py consults
``stall_threshold_s``) calls ``trigger(reason)`` — the ring freezes,
and a background thread writes a **diagnostic bundle** (flight ring +
recent spans + metrics snapshot + config fingerprint) as one JSON file
under ``bundle_dir``. Captures are throttled by ``cooldown_s`` so a
sustained incident produces one bundle, not a disk flood. ``GET/POST
/debug/flight`` (runtime/health.py) serve the ring and take manual
captures.

Env knobs (read once at import; ``configure()`` overrides):
``DTPU_FLIGHT_CAPACITY`` (ring slots, default 512, 0 disables),
``DTPU_FLIGHT_DIR`` (bundle directory, default /tmp/dtpu-flight),
``DTPU_FLIGHT_STALL_S`` (decode-stall trigger threshold, default 2.0,
0 disables), ``DTPU_FLIGHT_COOLDOWN_S`` (default 300).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

import numpy as np

from dynamo_tpu.runtime import journal as journal_mod
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("flight")

# Ring columns, in record() argument order. "tokens" (decode tokens
# emitted by the window) rides with "dur_s" (dispatch -> readback device
# time) so the perf plane's roofline attribution is replayable from a
# frozen ring, not only from live gauges.
FIELDS = ("t_mono", "dur_s", "active", "waiting", "free_pages",
          "chunk_tokens", "chunks_inflight", "preempts", "brownout",
          "stall_s", "step", "tokens")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw in (None, "") else int(raw)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return default if raw in (None, "") else float(raw)


class FlightRecorder:
    """Fixed-slot ring of per-window records (preallocated numpy
    columns; single engine-thread writer, any-thread readers)."""

    def __init__(self, capacity: int = 512, enabled: bool = True):
        self.capacity = max(1, capacity)
        self.enabled = enabled and capacity > 0
        self._cols = {name: np.zeros(self.capacity, np.float64)
                      for name in FIELDS}
        self._idx = 0
        self._count = 0
        # Preallocated cell, not a Python int: the idle-stable skip
        # path must retain no fresh objects (asserted by tracemalloc in
        # tests/test_slo.py).
        self._skipped = np.zeros(1, np.int64)
        self.frozen = False
        self.frozen_reason = ""
        self._was_idle = False
        # Guards freeze/dump vs. the writer; record() holds it only for
        # the column stores (sub-microsecond, no allocation).
        self._lock = threading.Lock()

    def record(self, t_mono: float, dur_s: float, active: int, waiting: int,
               free_pages: int, chunk_tokens: int, chunks_inflight: int,
               preempts: int, brownout: int, stall_s: float,
               step: int, tokens: int = 0) -> bool:
        """One engine-window row. Idle-stable windows (no active slots,
        no waiters, no chunk work — same as the previous call) are
        skipped without touching the ring. Returns False when the row
        was REJECTED (disabled / frozen mid-capture) so the caller
        keeps accumulating its deltas instead of losing them."""
        if not self.enabled or self.frozen:
            return False
        idle = active == 0 and waiting == 0 and chunks_inflight == 0 \
            and chunk_tokens == 0
        if idle and self._was_idle:
            self._skipped[0] += 1
            return True
        self._was_idle = idle
        with self._lock:
            i = self._idx
            cols = self._cols
            cols["t_mono"][i] = t_mono
            cols["dur_s"][i] = dur_s
            cols["active"][i] = active
            cols["waiting"][i] = waiting
            cols["free_pages"][i] = free_pages
            cols["chunk_tokens"][i] = chunk_tokens
            cols["chunks_inflight"][i] = chunks_inflight
            cols["preempts"][i] = preempts
            cols["brownout"][i] = brownout
            cols["stall_s"][i] = stall_s
            cols["step"][i] = step
            cols["tokens"][i] = tokens
            self._idx = (i + 1) % self.capacity
            if self._count < self.capacity:
                self._count += 1
        return True

    # -- freeze / dump --------------------------------------------------------
    def freeze(self, reason: str) -> bool:
        """Stop overwriting (first freeze wins). Returns True when this
        call did the freezing."""
        with self._lock:
            if self.frozen:
                return False
            self.frozen = True
            self.frozen_reason = reason
            return True

    def thaw(self) -> None:
        with self._lock:
            self.frozen = False
            self.frozen_reason = ""

    def clear(self) -> None:
        """Drop all recorded windows (tests, operator reset)."""
        with self._lock:
            self._idx = 0
            self._count = 0
            self._skipped[0] = 0
            self._was_idle = False

    def dump(self) -> list[dict]:
        """Ring contents oldest-first as dicts (the /debug/flight and
        bundle payload)."""
        with self._lock:
            n = self._count
            start = (self._idx - n) % self.capacity
            order = [(start + k) % self.capacity for k in range(n)]
            rows = []
            for i in order:
                row = {name: float(col[i])
                       for name, col in self._cols.items()}
                for name in ("active", "waiting", "free_pages",
                             "chunk_tokens", "chunks_inflight", "preempts",
                             "brownout", "step", "tokens"):
                    row[name] = int(row[name])
                rows.append(row)
            return rows

    @property
    def skipped_idle(self) -> int:
        return int(self._skipped[0])

    def meta(self) -> dict:
        return {"enabled": self.enabled, "capacity": self.capacity,
                "records": self._count, "skipped_idle": self.skipped_idle,
                "frozen": self.frozen, "frozen_reason": self.frozen_reason}


# -- process-global recorder + anomaly capture ---------------------------------

_RECORDER = FlightRecorder(
    capacity=_env_int("DTPU_FLIGHT_CAPACITY", 512))

#: Decode-stall trigger threshold consulted by the engine loop (0
#: disables the automatic trigger; the manual POST /debug/flight and
#: SLO-page triggers are independent of it).
stall_threshold_s = _env_float("DTPU_FLIGHT_STALL_S", 2.0)

_bundle_dir = os.environ.get("DTPU_FLIGHT_DIR", "/tmp/dtpu-flight")
_cooldown_s = _env_float("DTPU_FLIGHT_COOLDOWN_S", 300.0)
_last_trigger_t = -1e18
_trigger_lock = threading.Lock()
_metrics_registry = None
_config_fingerprint: dict = {}
triggers_total = 0


def get_recorder() -> FlightRecorder:
    return _RECORDER


def configure(metrics=None, config_fingerprint: dict | None = None,
              bundle_dir: str | None = None,
              stall_s: float | None = None,
              cooldown_s: float | None = None) -> None:
    """Entrypoint wiring: the metrics registry + config identity that
    go into bundles, and optional knob overrides."""
    global _metrics_registry, _config_fingerprint, _bundle_dir
    global stall_threshold_s, _cooldown_s
    if metrics is not None:
        _metrics_registry = metrics
    if config_fingerprint is not None:
        _config_fingerprint = config_fingerprint
    if bundle_dir is not None:
        _bundle_dir = bundle_dir
    if stall_s is not None:
        stall_threshold_s = stall_s
    if cooldown_s is not None:
        _cooldown_s = cooldown_s


def _fingerprint_payload() -> dict:
    body = json.dumps(_config_fingerprint, sort_keys=True, default=str)
    return {"config": _config_fingerprint,
            "sha256": hashlib.sha256(body.encode()).hexdigest()}


def capture_bundle(reason: str, out_dir: str | None = None) -> str:
    """Write one diagnostic bundle NOW (blocking; call off the loop).
    Returns the bundle path."""
    from dynamo_tpu.runtime import tracing

    out_dir = out_dir or _bundle_dir
    os.makedirs(out_dir, exist_ok=True)
    rec = _RECORDER
    ts = time.time()
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in reason)[:64]
    path = os.path.join(out_dir, f"flight-{int(ts)}-{safe_reason}.json")
    span_rec = tracing.get_recorder()
    bundle = {
        "reason": reason,
        "ts": ts,
        "flight": {"meta": rec.meta(), "windows": rec.dump()},
        "spans": span_rec.export_chrome(),
        "metrics": (_metrics_registry.expose().decode()
                    if _metrics_registry is not None else None),
        # The recent decision-plane slice: one bundle is a complete
        # incident artifact — what the engine was doing (flight ring),
        # what requests were doing (spans), and WHY the fleet acted
        # (journal), side by side.
        "journal": journal_mod.get_journal().snapshot(limit=256),
        "config_fingerprint": _fingerprint_payload(),
    }
    with open(path, "w") as fh:
        json.dump(bundle, fh)
    log.warning("flight bundle written: %s (%d windows, reason=%s)",
                path, len(bundle["flight"]["windows"]), reason)
    return path


def trigger(reason: str, clock=time.monotonic) -> bool:
    """Anomaly hook (SLO page, decode-stall spike): freeze the ring and
    write a bundle on a background thread. Throttled by the cooldown;
    returns True when a capture was actually started."""
    global _last_trigger_t, triggers_total
    with _trigger_lock:
        now = clock()
        if now - _last_trigger_t < _cooldown_s:
            return False
        _last_trigger_t = now
        triggers_total += 1
    _RECORDER.freeze(reason)
    # Decision plane: an anomaly capture is itself a fleet decision.
    # Cause: the SLO page that pulled the trigger, else (decode-stall
    # path) the chaos injection that froze the engine, when either is
    # on the recent record.
    journal_mod.emit(
        EventKind.FLIGHT_BUNDLE,
        cause=(journal_mod.recent_ref(EventKind.SLO_ALERT_FIRE)
               if reason.startswith("slo_burn")
               else journal_mod.recent_ref(EventKind.CHAOS_INJECT)),
        reason=reason)

    def _write() -> None:
        try:
            capture_bundle(reason)
        except Exception:  # noqa: BLE001 — diagnostics must never crash serving
            log.exception("flight bundle capture failed")
        finally:
            _RECORDER.thaw()

    threading.Thread(target=_write, name="flight-bundle",
                     daemon=True).start()
    return True


def on_slo_page(target: str, severity: str) -> None:
    """SloPlane.on_page adapter: page-severity alerts freeze + capture."""
    if severity == "fast":
        trigger(f"slo_burn_{target}")
