"""Endpoint client: the egress half of the request/response plane.

Capability parity with reference PushRouter (lib/runtime/src/pipeline/network/
egress/push_router.rs:29-54 — Random / RoundRobin / Direct routing; the KV mode
layers on top in dynamo_tpu.llm.kv_router) and component Client/InstanceSource
(component/client.rs:285): instances are discovered from a prefix watch and the
live set updates as leases appear/expire. Responses stream back multiplexed on
one duplex TCP connection per instance (vs the reference's NATS request +
reverse-TCP response design, egress/addressed_router.rs:69).
"""

from __future__ import annotations

import asyncio
import itertools
import random
import time
import uuid
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import chaos
from dynamo_tpu.runtime.component import Endpoint, Instance, instance_prefix
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.errors import (EngineError, NoInstancesError,
                                       OverloadedError, StreamIncompleteError)
from dynamo_tpu.runtime.frame import read_frame, write_frame
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.overload import BreakerBoard

log = get_logger("client")

_SENTINEL = object()


class _InstanceConn:
    """One multiplexed connection to an instance."""

    def __init__(self, instance: Instance):
        self.instance = instance
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._streams: dict[str, asyncio.Queue] = {}
        self._reader_task: asyncio.Task | None = None
        self._send_lock = asyncio.Lock()
        self.alive = False
        # Set when the instance deregisters while streams are in flight:
        # the connection drains them and closes itself once idle.
        self.retire_when_idle = False

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.instance.host, self.instance.port)
        self.alive = True
        self._reader_task = asyncio.create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_frame(self._reader, chaos_site="client")
                q = self._streams.get(msg.get("rid"))
                if q is None:
                    continue
                t = msg.get("t")
                if t == "data":
                    q.put_nowait(("data", msg.get("p"), msg.get("s")))
                elif t == "final":
                    q.put_nowait(("final", None, msg.get("s")))
                elif t == "err":
                    q.put_nowait(("err", msg.get("e"), None))
        except (asyncio.IncompleteReadError, ConnectionError, ValueError, OSError):
            pass
        finally:
            self.alive = False
            for q in self._streams.values():
                q.put_nowait(("lost", None, None))

    async def send(self, obj: dict) -> None:
        if not self.alive:
            raise ConnectionError("instance connection lost")
        async with self._send_lock:
            await write_frame(self._writer, obj, chaos_site="client")

    def open_stream(self, rid: str) -> asyncio.Queue:
        # Per-stream response frames: bounded by the request's token
        # budget (the worker emits one data frame per token, then
        # final); bounding here would force the shared read loop to
        # block — a slow consumer would head-of-line-block every other
        # stream on this connection.
        # dtpu: ignore[unbounded-queue] -- see above
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        return q

    def close_stream(self, rid: str) -> None:
        self._streams.pop(rid, None)
        if self.retire_when_idle and not self._streams:
            self.close()

    def close(self) -> None:
        self.alive = False
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()


class EndpointClient:
    def __init__(self, runtime, endpoint: Endpoint, router_mode: str = "round_robin"):
        self._runtime = runtime
        self._endpoint = endpoint
        self.router_mode = router_mode
        self._instances: dict[int, Instance] = {}
        self._conns: dict[int, _InstanceConn] = {}
        self._conn_locks: dict[int, asyncio.Lock] = {}
        self._rr = itertools.count()
        self._watch = None
        self._watch_task: asyncio.Task | None = None
        self._instances_event = asyncio.Event()
        # Per-worker circuit breakers (runtime/overload.py): typed
        # transport/handler failures and latency outliers open a
        # worker's breaker; selection skips it until a half-open probe
        # succeeds. The KV router shares this board via its scheduler.
        self.breakers = BreakerBoard(
            getattr(runtime.config, "overload", None),
            metrics=getattr(runtime, "metrics", None))
        # Throttle for the all-breakers-open journal shed event.
        self._breakers_shed_t = -1e18
        self._breakers_shed_n = 0

    async def start(self) -> None:
        if self._runtime.has_discovery:
            prefix = instance_prefix(self._endpoint.component.namespace,
                                     self._endpoint.component.name,
                                     self._endpoint.name)
            self._watch = await self._runtime.coordinator_client.watch_prefix(prefix)
            for entry in self._watch.snapshot:
                self._add_instance(Instance.from_wire(entry["v"]))
            self._watch_task = asyncio.create_task(self._watch_loop())

    def add_static_instance(self, instance: Instance) -> None:
        """Static mode: directly-addressed instance (reference static mode,
        distributed.rs:178)."""
        self._add_instance(instance)

    def _add_instance(self, instance: Instance) -> None:
        self._instances[instance.instance_id] = instance
        self._instances_event.set()

    def _remove_instance(self, instance_id: int) -> None:
        self._instances.pop(instance_id, None)
        self.breakers.remove(instance_id)
        conn = self._conns.pop(instance_id, None)
        if conn:
            # Deregistration only stops NEW routing to the instance.
            # In-flight streams on a healthy TCP connection drain to
            # completion: a lease blip (keepalive starved under load)
            # must not kill a stream that the worker is still serving —
            # but only within retire_drain_s (RuntimeConfig /
            # DTPU_RETIRE_DRAIN_S), so a partitioned worker can't hang
            # its streams forever. Crashed workers close the TCP
            # connection themselves (kernel FIN/RST -> immediate "lost"
            # wakeup); the drain deadline covers the silent cases —
            # network partition, host power loss — where no packet ever
            # arrives and lease expiry is the only death signal.
            if conn._streams:
                conn.retire_when_idle = True
                asyncio.get_running_loop().call_later(
                    self._runtime.config.retire_drain_s, conn.close)
            else:
                conn.close()
        if not self._instances:
            self._instances_event.clear()

    async def _watch_loop(self) -> None:
        async for event in self._watch:
            if event["event"] == "put":
                self._add_instance(Instance.from_wire(event["value"]))
            else:
                # key tail is the hex instance id
                try:
                    iid = int(event["key"].rsplit("/", 1)[-1], 16)
                except ValueError:
                    continue
                self._remove_instance(iid)

    # -- instance selection ---------------------------------------------------
    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    async def wait_for_instances(self, timeout: float = 30.0) -> list[int]:
        try:
            await asyncio.wait_for(self._instances_event.wait(), timeout)
        except asyncio.TimeoutError:
            raise NoInstancesError(
                f"no instances for {self._endpoint.path} after {timeout}s") from None
        return self.instance_ids()

    def _select(self, mode: str, instance_id: int | None) -> Instance:
        ids = self.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self._endpoint.path}")
        if mode == "direct":
            if instance_id not in self._instances:
                raise NoInstancesError(
                    f"instance {instance_id:x} not found for {self._endpoint.path}")
            return self._instances[instance_id]
        # Circuit breakers: skip workers whose breaker is open (direct
        # mode bypasses — the KV router already filtered, and admin ops
        # must be able to reach a sick instance deliberately).
        healthy = self.breakers.admitted(ids)
        if not healthy:
            from dynamo_tpu.runtime import journal
            from dynamo_tpu.runtime.journal import EventKind
            now = time.monotonic()
            if now - self._breakers_shed_t >= 1.0:
                # Throttled like the limiter's shed events: one journal
                # event speaks for the storm, with the suppressed tally.
                journal.emit(
                    EventKind.SHED,
                    cause=journal.recent_ref(EventKind.BREAKER_TRANSITION),
                    reason="breakers_open", instances=len(ids),
                    endpoint=self._endpoint.path,
                    suppressed=self._breakers_shed_n)
                self._breakers_shed_t = now
                self._breakers_shed_n = 0
            else:
                self._breakers_shed_n += 1
            raise OverloadedError(
                f"all {len(ids)} instances for {self._endpoint.path} are "
                "circuit-open; retry shortly")
        if mode == "random":
            return self._instances[random.choice(healthy)]
        # round_robin
        return self._instances[healthy[next(self._rr) % len(healthy)]]

    async def _conn_for(self, instance: Instance) -> _InstanceConn:
        # Per-instance lock: concurrent first requests share one connection
        # instead of racing open_connection and leaking the losers.
        lock = self._conn_locks.setdefault(instance.instance_id, asyncio.Lock())
        async with lock:
            conn = self._conns.get(instance.instance_id)
            if conn is None or not conn.alive:
                conn = _InstanceConn(instance)
                await conn.connect()
                self._conns[instance.instance_id] = conn
            return conn

    # -- request issue --------------------------------------------------------
    async def generate(self, request: Any, context: Context | None = None,
                       mode: str | None = None,
                       instance_id: int | None = None) -> AsyncIterator[Any]:
        """Route a request and return its response stream."""
        ctx = context or Context()
        mode = mode or self.router_mode
        if instance_id is not None:
            mode = "direct"
        instance = self._select(mode, instance_id)
        return self._stream(instance, request, ctx)

    async def direct(self, request: Any, instance_id: int,
                     context: Context | None = None) -> AsyncIterator[Any]:
        return await self.generate(request, context, mode="direct",
                                   instance_id=instance_id)

    async def round_robin(self, request: Any, context: Context | None = None
                          ) -> AsyncIterator[Any]:
        return await self.generate(request, context, mode="round_robin")

    async def random(self, request: Any, context: Context | None = None
                     ) -> AsyncIterator[Any]:
        return await self.generate(request, context, mode="random")

    async def _stream(self, instance: Instance, request: Any, ctx: Context
                      ) -> AsyncIterator[Any]:
        rid = uuid.uuid4().hex
        iid = instance.instance_id
        breakers = self.breakers
        try:
            conn = await self._conn_for(instance)
            q = conn.open_stream(rid)
            await conn.send({"t": "req", "rid": rid, "ctx": ctx.to_wire(),
                             "p": request})
        except (ConnectionError, OSError) as exc:
            # Don't remove the instance from the routing set: its registration
            # (and lease) may still be live and discovery is the single source
            # of truth — removal happens only on a watch delete event. Just
            # drop the dead connection so the next attempt redials.
            conn = self._conns.pop(instance.instance_id, None)
            if conn:
                conn.close()
            breakers.record_failure(iid)
            raise StreamIncompleteError(
                f"Stream ended before generation completed "
                f"(connect to {instance.instance_id:x} failed: {exc})") from exc
        breakers.on_dispatch(iid)
        # Worker attribution for the request's accounting record: the
        # LAST dispatch wins, which is what migration semantics want.
        ctx.values["worker_id"] = f"{iid:x}"
        sent_t = time.monotonic()
        first_latency: float | None = None
        failed = False
        stop_sent = False
        # A stop/kill issued while we're blocked on the queue must reach the
        # worker immediately (not only after the next frame arrives): a single
        # watcher pushes a wakeup sentinel into the stream queue when the
        # context cancels — zero per-frame overhead on the token hot path.
        stop_t = asyncio.ensure_future(ctx.wait_stopped())
        stop_t.add_done_callback(lambda _: q.put_nowait(("wake", None, None)))
        # Data frames carry per-stream sequence numbers; track them so a
        # lost frame (worker bug, chaos) fails TYPED instead of silently
        # shortening the stream, and a duplicated frame is dropped
        # instead of double-delivering tokens.
        expected_seq = 0
        idle_s = self._runtime.config.stream_idle_timeout_s
        try:
            while True:
                if ctx.is_killed and not stop_sent:
                    stop_sent = True
                    try:
                        await conn.send({"t": "kill", "rid": rid})
                    except (ConnectionError, OSError):
                        pass
                    return
                if ctx.is_stopped and not stop_sent:
                    stop_sent = True
                    try:
                        await conn.send({"t": "stop", "rid": rid})
                    except (ConnectionError, OSError):
                        pass
                try:
                    if idle_s and idle_s > 0:
                        # An idle deadline between frames: a zombie
                        # connection (worker wedged, final frame lost)
                        # must become a typed migration trigger, not an
                        # indefinite hang.
                        kind, payload, seq = await asyncio.wait_for(
                            q.get(), idle_s)
                    else:
                        kind, payload, seq = await q.get()
                except asyncio.TimeoutError:
                    try:
                        await conn.send({"t": "kill", "rid": rid})
                    except (ConnectionError, OSError):
                        pass
                    failed = True
                    breakers.record_failure(iid)
                    raise StreamIncompleteError(
                        f"Stream ended before generation completed (no "
                        f"frames from {instance.instance_id:x} for "
                        f"{idle_s:g}s)") from None
                if kind == "wake":
                    continue  # cancellation wakeup; loop top sends stop/kill
                if kind == "data":
                    if chaos.ACTIVE and chaos.fire("stream.disconnect",
                                                   "client"):
                        conn.close()  # read loop broadcasts ("lost")
                        continue
                    if first_latency is None:
                        first_latency = time.monotonic() - sent_t
                    if seq is not None:
                        if seq < expected_seq:
                            continue  # duplicate frame: already delivered
                        if seq > expected_seq:
                            failed = True
                            breakers.record_failure(iid)
                            raise StreamIncompleteError(
                                "Stream ended before generation completed "
                                f"(frame gap: expected #{expected_seq}, "
                                f"got #{seq})")
                        expected_seq += 1
                    yield payload
                elif kind == "final":
                    if seq is not None and seq != expected_seq:
                        failed = True
                        breakers.record_failure(iid)
                        raise StreamIncompleteError(
                            "Stream ended before generation completed "
                            f"(final after #{expected_seq} of {seq} frames)")
                    return
                elif kind == "err":
                    if isinstance(payload, str) and (
                            payload == "incomplete"
                            or payload.startswith("incomplete:")):
                        # "incomplete[:reason]": the worker declared the
                        # stream dead (drain kill, handler GeneratorExit).
                        # The optional reason ("role_flip") rides the
                        # typed error into migration attribution.
                        _, _, why = payload.partition(":")
                        failed = True
                        breakers.record_failure(iid)
                        raise StreamIncompleteError(reason=why or None)
                    from dynamo_tpu.runtime.errors import (
                        AdapterNotFoundError, InvalidRequestError,
                        RateLimitedError, RoleTransitionError)
                    # Wire-typed errors: decode every class that carries
                    # a WIRE_PREFIX so HTTP status / retry semantics
                    # survive remote deployment. One explicit branch per
                    # class — the wire-error-taxonomy lint checks these
                    # references stay in sync with runtime/errors.py.
                    if isinstance(payload, str):
                        if payload.startswith(InvalidRequestError.WIRE_PREFIX):
                            # The caller's fault, not the worker's: no
                            # breaker signal.
                            raise InvalidRequestError(
                                payload[len(InvalidRequestError.WIRE_PREFIX):])
                        if payload.startswith(RateLimitedError.WIRE_PREFIX):
                            raise RateLimitedError(
                                payload[len(RateLimitedError.WIRE_PREFIX):])
                        if payload.startswith(
                                AdapterNotFoundError.WIRE_PREFIX):
                            # A naming error (the adapter slug resolved
                            # to a worker without the adapter), not a
                            # worker-health signal.
                            raise AdapterNotFoundError(
                                payload[len(
                                    AdapterNotFoundError.WIRE_PREFIX):])
                        if payload.startswith(RoleTransitionError.WIRE_PREFIX):
                            # Control-verb fencing rejection: the caller's
                            # fault (stale epoch), not worker health.
                            raise RoleTransitionError(
                                payload[len(RoleTransitionError.WIRE_PREFIX):])
                        if payload.startswith(OverloadedError.WIRE_PREFIX):
                            # Saturated worker: a breaker failure signal
                            # so selection steers away while it drains.
                            failed = True
                            breakers.record_failure(iid)
                            raise OverloadedError(
                                payload[len(OverloadedError.WIRE_PREFIX):])
                        if payload == "killed":
                            # Client-initiated kill echoed back: not a
                            # worker-health signal.
                            raise EngineError(payload)
                    failed = True
                    breakers.record_failure(iid)
                    raise EngineError(payload)
                else:  # lost
                    failed = True
                    breakers.record_failure(iid)
                    raise StreamIncompleteError(
                        "Stream ended before generation completed "
                        f"(connection to {instance.instance_id:x} lost)")
        finally:
            # Breaker outcome: a stream that delivered frames and saw no
            # failure counts as a success even when the consumer
            # abandons the generator early (HTTP pipelines break on
            # finish_reason without draining the final frame). Latency
            # sample = time to FIRST frame (the TTFT analogue): total
            # stream time scales with max_tokens, the client's choice,
            # not the worker's health.
            if not failed and first_latency is not None:
                breakers.record_success(iid, first_latency)
            stop_t.cancel()
            conn.close_stream(rid)

    async def close(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch:
            await self._watch.cancel()
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
