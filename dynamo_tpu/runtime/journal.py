"""Fleet event journal: the decision plane's typed, causal event log.

Every subsystem that acts autonomously — breaker opens, AIMD sheds,
brownout levels, preemptions, migrations, role flips, planner reconfig
decisions, SLO alerts, chaos injections, flight-recorder captures —
used to announce its decision only as a log line or a counter bump.
This module gives those decisions one structured home so the fleet can
answer "**why** did it do that, in what order, triggered by what":

- A closed ``EventKind`` taxonomy. ``emit()`` rejects unknown kinds,
  and the ``untyped-journal-event`` lint rule
  (dynamo_tpu/analysis/rules_journal.py) keeps call sites on the typed
  constants — no ad-hoc string kinds, no raw dict publishes onto the
  journal subject.
- Each event carries a process-monotonic ``seq``, wall-clock ``ts``,
  the emitting worker id, the request ``trace_id`` when emitted in a
  request context, and a ``cause`` back-reference (another event's
  ``worker#seq`` ref, or a trace id) — so causal chains are explicit at
  emit time, not reconstructed by log archaeology.
- ``Journal`` is a bounded in-process ring (same non-blocking
  discipline as the flight recorder / ``RequestLedger``): ``emit()``
  takes one lock for the append and never blocks on I/O. The optional
  JSONL sink rides the ``Recorder`` queue (llm/recorder.py).
- ``JournalPublisher`` ships seq-fenced deltas on the event plane
  (same pattern as ``KvInventoryPublisher``); the frontend's
  ``TimelineCollector`` (llm/timeline.py) feeds them into
  ``FleetTimeline``, which merges per-worker streams into one causally
  ordered fleet timeline served at ``GET /debug/timeline``
  (runtime/health.py). Seq fencing never silently reorders across a
  worker restart: a changed ``boot`` id or a skipped seq range becomes
  a typed ``journal_gap`` event in the merged stream.

Env knobs (read at configure time): ``DTPU_JOURNAL_CAPACITY`` (ring
slots, default 2048, 0 disables), ``DTPU_JOURNAL_PATH`` (JSONL sink).

docs/OBSERVABILITY.md "Decision plane" documents the operator surface;
``scripts/timeline_view.py`` renders an incident as a cause tree.
"""

from __future__ import annotations

import collections
import os
import threading
import time
import uuid
from typing import Callable

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("journal")


class EventKind:
    """The closed journal taxonomy. Emit sites MUST use these constants
    (enforced by the ``untyped-journal-event`` lint rule)."""

    BREAKER_TRANSITION = "breaker_transition"
    SHED = "shed"
    BROWNOUT_CHANGE = "brownout_change"
    PREEMPT = "preempt"
    MIGRATION = "migration"
    ROLE_FLIP_REQUESTED = "role_flip_requested"
    ROLE_FLIP_DRAINING = "role_flip_draining"
    ROLE_FLIP_DONE = "role_flip_done"
    ROLE_FLIP_REJECTED = "role_flip_rejected"
    SLO_ALERT_FIRE = "slo_alert_fire"
    SLO_ALERT_CLEAR = "slo_alert_clear"
    FLIGHT_BUNDLE = "flight_bundle"
    CHAOS_INJECT = "chaos_inject"
    WORKER_JOIN = "worker_join"
    WORKER_LEAVE = "worker_leave"
    PLANNER_DECISION = "planner_decision"
    CANARY_OK = "canary_ok"
    CANARY_FAIL = "canary_fail"
    # Autoscaling (planner/capacity.py + llm/standby.py): a pre-warmed
    # standby finished its warmup and parked (ready), a scale-out
    # directive promoted it into the serving fleet, and the scale-in
    # retire verb drained a serving worker out of it.
    STANDBY_READY = "standby_ready"
    STANDBY_PROMOTE = "standby_promote"
    SCALE_RETIRE = "scale_retire"
    # KV federation (engine/kvbm.py + llm/kv_plane.py): tier placement
    # decisions — watermark demotions down the ladder, promote-on-hit
    # back up it, and cross-worker block pulls over the KV plane.
    KV_DEMOTE = "kv_demote"
    KV_PROMOTE = "kv_promote"
    KV_PEER_PULL = "kv_peer_pull"
    # Synthesized by the timeline merge, never by emit sites: a worker's
    # delta stream skipped seqs (publisher overflow, dropped frames) or
    # restarted (new boot id).
    JOURNAL_GAP = "journal_gap"


EVENT_KINDS = frozenset(
    v for k, v in vars(EventKind).items() if not k.startswith("_"))


def journal_subject(namespace: str) -> str:
    """The pub/sub subject journal deltas ride (one per namespace: the
    timeline merge wants EVERY component's decisions in one stream)."""
    return f"ns.{namespace}.journal"


def event_ref(worker: str, seq: int) -> str:
    """The globally resolvable identity of one event."""
    return f"{worker}#{seq}"


class Journal:
    """Bounded ring of typed events. Thread-safe: emits come from the
    event loop AND engine threads; ``emit()`` holds the lock only for
    the append (no I/O, no allocation beyond the event dict)."""

    def __init__(self, capacity: int = 2048, worker: str | None = None,
                 metrics=None, clock: Callable[[], float] = time.time):
        self.capacity = max(0, capacity)
        self.enabled = self.capacity > 0
        self.worker = worker or "proc"
        # A fresh id per Journal instance: consumers detect a worker
        # restart (seq reset) by the boot change, not by guessing.
        self.boot = uuid.uuid4().hex[:8]
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=self.capacity or 1)
        self._seq = 0
        self.emitted_total = 0
        # Events evicted from the ring before any publisher shipped them
        # (JournalPublisher.flush detects the seq hole and adds here).
        self.dropped_overflow = 0
        # kind -> (seq, ref) of the newest event of that kind, for
        # cause attribution by downstream emit sites.
        self._recent: dict[str, tuple[int, str]] = {}
        self._sink = None
        self._m_events = self._m_dropped = None
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, metrics) -> None:
        m = metrics.namespace("journal")
        self._m_events = m.counter(
            "journal_events_total", "Fleet journal events emitted",
            ["kind"])
        self._m_dropped = m.counter(
            "journal_dropped_total",
            "Journal events lost to ring overflow before publication")

    def configure_sink(self, path: str | None) -> None:
        """Optional durable JSONL sink (non-blocking Recorder queue)."""
        if path:
            from dynamo_tpu.llm.recorder import Recorder
            self._sink = Recorder(path)
        else:
            self._sink = None

    # -- emit ------------------------------------------------------------------
    def emit(self, kind: str, *, cause: str | None = None,
             trace_id: str | None = None, worker: str | None = None,
             **attrs) -> str:
        """Record one typed event; returns its ``worker#seq`` ref (the
        handle a downstream emitter passes as its own ``cause``).
        Unknown kinds are a bug at the call site: ValueError."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown journal event kind {kind!r} (use the EventKind "
                "constants from runtime/journal.py)")
        origin = worker or self.worker
        with self._lock:
            self._seq += 1
            seq = self._seq
            ref = event_ref(origin, seq)
            event = {"kind": kind, "seq": seq, "ts": self._clock(),
                     "worker": origin, "ref": ref, "trace_id": trace_id,
                     "cause": cause, "attrs": attrs}
            if self.enabled:
                self._ring.append(event)
            self._recent[kind] = (seq, ref)
            self.emitted_total += 1
        if self._m_events is not None:
            self._m_events.inc(kind=kind)
        sink = self._sink
        if sink is not None:
            try:
                sink.start()  # idempotent; needs a running loop
            except RuntimeError:
                pass  # engine-thread caller with no loop: ring only
            else:
                sink.record(event)
        return ref

    def recent_ref(self, *kinds: str) -> str | None:
        """The ref of the newest event among ``kinds`` — how an emit
        site names its most plausible upstream cause without threading
        refs through every call path."""
        best: tuple[int, str] | None = None
        with self._lock:
            for kind in kinds:
                entry = self._recent.get(kind)
                if entry is not None and (best is None or entry[0] > best[0]):
                    best = entry
        return best[1] if best else None

    # -- read ------------------------------------------------------------------
    def since(self, last_seq: int) -> tuple[list[dict], int]:
        """(events with seq > last_seq oldest-first, missed count).
        ``missed`` > 0 means the ring already evicted events the caller
        never saw — the publisher reports it so the timeline can mark a
        typed gap instead of silently skipping."""
        with self._lock:
            events = [e for e in self._ring if e["seq"] > last_seq]
            missed = 0
            if events:
                missed = events[0]["seq"] - last_seq - 1
            elif self._seq > last_seq:
                missed = self._seq - last_seq
            return events, max(0, missed)

    def note_dropped(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self.dropped_overflow += n
        if self._m_dropped is not None:
            self._m_dropped.inc(n)

    @property
    def seq(self) -> int:
        return self._seq

    def events(self, limit: int = 0) -> list[dict]:
        """Ring contents oldest-first (the newest ``limit`` when set)."""
        with self._lock:
            rows = list(self._ring)
        return rows[-limit:] if limit > 0 else rows

    def snapshot(self, limit: int = 512) -> dict:
        return {
            "worker": self.worker,
            "boot": self.boot,
            "enabled": self.enabled,
            "capacity": self.capacity,
            "seq": self._seq,
            "emitted_total": self.emitted_total,
            "dropped_overflow": self.dropped_overflow,
            "events": self.events(limit),
        }

    async def close(self) -> None:
        if self._sink is not None:
            await self._sink.close()


# -- process-global journal ----------------------------------------------------

def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw in (None, "") else int(raw)


_JOURNAL = Journal(capacity=_env_int("DTPU_JOURNAL_CAPACITY", 2048))


def get_journal() -> Journal:
    return _JOURNAL


def configure(worker: str | None = None, metrics=None,
              capacity: int | None = None,
              path: str | None = None) -> Journal:
    """Entrypoint wiring (worker mains, frontend, launcher): the worker
    identity events are attributed to, the metrics registry, and the
    optional JSONL sink. The ring (and its seq fence) is preserved
    unless capacity changes."""
    global _JOURNAL
    if capacity is not None and capacity != _JOURNAL.capacity:
        _JOURNAL = Journal(capacity=capacity, worker=worker or _JOURNAL.worker)
    if worker is not None:
        _JOURNAL.worker = worker
    if metrics is not None:
        _JOURNAL.bind_metrics(metrics)
    if path is None:
        path = os.environ.get("DTPU_JOURNAL_PATH") or None
    if path:
        _JOURNAL.configure_sink(path)
    return _JOURNAL


def emit(kind: str, *, cause: str | None = None, trace_id: str | None = None,
         worker: str | None = None, **attrs) -> str:
    """Module-level emit on the process journal (the form every
    instrumented subsystem uses: ``journal.emit(EventKind.X, ...)``)."""
    return _JOURNAL.emit(kind, cause=cause, trace_id=trace_id,
                         worker=worker, **attrs)


def recent_ref(*kinds: str) -> str | None:
    return _JOURNAL.recent_ref(*kinds)


# -- event-plane delta publisher ----------------------------------------------


class JournalPublisher:
    """Ships journal deltas on the event plane, seq-fenced (same shape
    as ``KvInventoryPublisher``): each message carries the worker id,
    the journal's ``boot``, the covered seq range, and any ``overflow``
    (events the ring evicted before this flush — the consumer marks a
    typed gap). ``client`` is anything with ``publish(subject, dict)``
    (a coordinator client); the planner passes its raw client."""

    def __init__(self, client, namespace: str, worker: str,
                 journal: Journal | None = None,
                 min_interval_s: float = 0.5, max_batch: int = 256):
        self._client = client
        self.subject = journal_subject(namespace)
        self.worker = worker
        self._journal = journal or get_journal()
        self.min_interval_s = min_interval_s
        self.max_batch = max_batch
        self._last_seq = 0
        self.published = 0
        self._periodic = None

    async def flush(self, force: bool = False) -> int:
        """Publish everything emitted since the last flush. Returns the
        number of events shipped."""
        journal = self._journal
        events, missed = journal.since(self._last_seq)
        if missed:
            journal.note_dropped(missed)
        if not events and not (force or missed):
            return 0
        shipped = 0
        while True:
            batch = events[:self.max_batch]
            events = events[self.max_batch:]
            payload = {
                "worker": self.worker,
                "boot": journal.boot,
                "first_seq": batch[0]["seq"] if batch else self._last_seq + 1,
                "last_seq": batch[-1]["seq"] if batch else self._last_seq,
                "overflow": missed,
                "events": batch,
            }
            await self._client.publish(self.subject, payload)
            self.published += 1
            shipped += len(batch)
            if batch:
                self._last_seq = batch[-1]["seq"]
            elif missed:
                # Everything in the hole was already evicted: advance
                # the fence past it or every flush re-reports the miss.
                self._last_seq += missed
            missed = 0  # reported once
            if not events:
                return shipped

    def start_periodic(self) -> None:
        import asyncio

        async def loop() -> None:
            while True:
                await asyncio.sleep(self.min_interval_s)
                try:
                    await self.flush()
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — telemetry, keep going
                    log.exception("journal delta publish failed")

        if self._periodic is None:
            self._periodic = asyncio.get_running_loop().create_task(loop())

    def stop_periodic(self) -> None:
        if self._periodic is not None:
            self._periodic.cancel()
            self._periodic = None


# -- fleet timeline merge ------------------------------------------------------


class FleetTimeline:
    """Merges per-worker journal delta streams into one causally
    ordered fleet timeline (sync core; the subscription loop lives in
    llm/timeline.py, same split as ``FleetInventory``).

    Fencing: per-worker ``(boot, last_seq)``. A delta with seqs at or
    below the fence is a replay/reorder and is dropped; a delta whose
    ``boot`` changed means the worker restarted — the fence resets and
    a typed ``journal_gap`` event marks the discontinuity instead of
    the old fence silently swallowing the fresh stream. A skipped seq
    range (publisher overflow, dropped frames) likewise becomes a
    ``journal_gap``. ``ApproxKvIndexer``-style staleness: stream state
    for a worker that stops publishing is pruned after ``ttl_s`` (its
    already-merged events stay — they are history)."""

    def __init__(self, ttl_s: float = 60.0, capacity: int = 8192,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        self.ttl_s = ttl_s
        self._clock = clock
        self._wall = wall_clock
        # worker -> {"boot", "last_seq", "rx_t"}
        self._streams: dict[str, dict] = {}
        self._events: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        self._gap_seq = 0
        self.applied = 0
        self.dropped_stale_seq = 0
        self.gaps = 0

    def _gap(self, worker: str, reason: str, **attrs) -> None:
        """Synthesize a typed journal_gap event in the merged stream
        (gaps get their own 'timeline' worker namespace so their refs
        can't collide with real worker seqs)."""
        self._gap_seq += 1
        self.gaps += 1
        self._events.append({
            "kind": EventKind.JOURNAL_GAP,
            "seq": self._gap_seq,
            "ts": self._wall(),
            "worker": "timeline",
            "ref": event_ref("timeline", self._gap_seq),
            "trace_id": None,
            "cause": None,
            "attrs": {"stream": worker, "reason": reason, **attrs},
        })

    def apply_delta(self, payload: dict) -> int:
        """Apply one publisher message; returns events merged."""
        worker = str(payload.get("worker") or "?")
        boot = str(payload.get("boot") or "")
        events = payload.get("events") or []
        stream = self._streams.get(worker)
        if stream is None:
            stream = self._streams[worker] = {
                "boot": boot, "last_seq": 0, "rx_t": self._clock()}
        elif boot and stream["boot"] != boot:
            # Restart: seqs reset. Without this reset the old fence
            # would silently drop (reorder) the entire fresh stream.
            self._gap(worker, "restart", old_boot=stream["boot"],
                      new_boot=boot)
            stream["boot"] = boot
            stream["last_seq"] = 0
        stream["rx_t"] = self._clock()
        overflow = int(payload.get("overflow") or 0)
        first = int(payload.get("first_seq") or 0)
        if overflow or (first and first > stream["last_seq"] + 1):
            missing = max(overflow, first - stream["last_seq"] - 1)
            self._gap(worker, "missed", missing=missing,
                      resume_seq=first)
        applied = 0
        for event in events:
            seq = int(event.get("seq") or 0)
            if seq <= stream["last_seq"]:
                self.dropped_stale_seq += 1
                continue
            stream["last_seq"] = seq
            row = dict(event)
            row.setdefault("worker", worker)
            row.setdefault("ref", event_ref(worker, seq))
            self._events.append(row)
            applied += 1
        self.applied += applied
        return applied

    def prune(self) -> list[str]:
        """Drop stream fences not heard from within ttl_s (deregistered
        or dead workers). Their merged events remain."""
        now = self._clock()
        dead = [w for w, s in self._streams.items()
                if now - s["rx_t"] > self.ttl_s]
        for w in dead:
            del self._streams[w]
        return dead

    def events(self, limit: int = 0) -> list[dict]:
        rows = sorted(self._events, key=lambda e: e["ts"])
        return rows[-limit:] if limit > 0 else rows

    def snapshot(self, limit: int = 512) -> dict:
        now = self._clock()
        return {
            "workers": {
                w: {"boot": s["boot"], "last_seq": s["last_seq"],
                    "age_s": round(now - s["rx_t"], 3),
                    "stale": now - s["rx_t"] > self.ttl_s}
                for w, s in sorted(self._streams.items())},
            "applied": self.applied,
            "dropped_stale_seq": self.dropped_stale_seq,
            "gaps": self.gaps,
            "events": self.events(limit),
        }


def merge_timeline(fleet_events: list[dict], local: Journal | None = None,
                   limit: int = 512) -> list[dict]:
    """One causally ordered stream: the fleet's merged events plus this
    process's own journal (the frontend emits sheds/breaker/SLO events
    locally — they never ride the event plane)."""
    rows = list(fleet_events)
    if local is not None:
        rows.extend(local.events())
    rows.sort(key=lambda e: e["ts"])
    return rows[-limit:] if limit > 0 else rows
