"""Core streaming-engine trait.

Capability parity with reference AsyncEngine (lib/runtime/src/engine.rs:207):
an engine maps one request to a stream of responses; every stream is associated
with a Context granting id/stop/kill. The pipeline operators (preprocessor,
backend/detokenizer, migration, router) all implement this same trait so they
compose into the frontend-to-worker request path (SURVEY.md call stack 3.1).
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.context import Context


class AsyncEngine(abc.ABC):
    """SingleIn -> ManyOut streaming engine."""

    @abc.abstractmethod
    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        """Return an async iterator of responses for ``request``.

        Implementations are async generators; cancellation is cooperative via
        ``context.is_stopped`` / generator close.
        """
        raise NotImplementedError


class Operator(AsyncEngine):
    """An engine stage wrapping a downstream engine (reference pipeline
    Operator, lib/runtime/src/pipeline/nodes.rs:122 — forward edge transforms
    the request, backward edge transforms the response stream)."""

    def __init__(self, inner: AsyncEngine | None = None):
        self.inner = inner

    def link(self, inner: AsyncEngine) -> "Operator":
        self.inner = inner
        return self
