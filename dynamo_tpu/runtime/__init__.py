"""Distributed runtime core (capability parity with reference lib/runtime).

Exposes the component addressing model (Namespace -> Component -> Endpoint ->
Instance), the streaming engine trait, the DistributedRuntime node singleton, and
the built-in control-plane coordinator that plays the role etcd + NATS play in the
reference (lib/runtime/src/distributed.rs:54-66).
"""

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.distributed import DistributedRuntime

__all__ = [
    "AsyncEngine",
    "Context",
    "DistributedRuntime",
    "RuntimeConfig",
]
