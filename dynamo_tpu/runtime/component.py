"""Component addressing model: Namespace -> Component -> Endpoint -> Instance.

Capability parity with reference lib/runtime/src/component.rs: components are
addressed ``{namespace}/{component}/{endpoint}``; live instances register
themselves under the ``instances/`` KV root with their lease so that clients can
discover and watch them (component.rs:74-98). Transport metadata in the
registration tells clients how to reach the instance (here: framed TCP host/port
instead of a NATS subject + reverse TCP — component.rs:82 TransportType).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Any, Awaitable, Callable

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

INSTANCE_ROOT = "instances/"
COMPONENT_ROOT = "dynamo://"


@dataclasses.dataclass(frozen=True)
class Instance:
    """A live endpoint instance (reference component.rs:98)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    host: str
    port: int

    @property
    def path(self) -> str:
        return (f"{INSTANCE_ROOT}{self.namespace}/{self.component}/"
                f"{self.endpoint}/{self.instance_id:x}")

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "Instance":
        return cls(**{f.name: data[f.name] for f in dataclasses.fields(cls)})


def instance_prefix(namespace: str, component: str, endpoint: str | None = None) -> str:
    base = f"{INSTANCE_ROOT}{namespace}/{component}/"
    return base if endpoint is None else f"{base}{endpoint}/"


class Namespace:
    def __init__(self, runtime: "DistributedRuntime", name: str):
        self._runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._runtime, self.name, name)


class Component:
    def __init__(self, runtime: "DistributedRuntime", namespace: str, name: str):
        self._runtime = runtime
        self.namespace = namespace
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._runtime, self, name)

    # Subjects for this component's event planes (reference kv_router.rs:56-65).
    def subject(self, plane: str) -> str:
        return f"ns.{self.namespace}.cp.{self.name}.{plane}"


class Endpoint:
    def __init__(self, runtime: "DistributedRuntime", component: Component, name: str):
        self._runtime = runtime
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    async def serve_endpoint(
        self,
        handler: Callable[..., Any],
        graceful_shutdown: bool = True,
        metrics_labels: dict[str, str] | None = None,
    ):
        """Serve ``handler`` (async generator fn (request, context) -> yields
        responses) as a discoverable instance. Reference:
        endpoint.serve_endpoint (bindings rust/lib.rs:519 -> component/endpoint.rs:65).
        Returns the EndpointServer (call .wait()/.shutdown())."""
        from dynamo_tpu.runtime.service import EndpointServer

        server = EndpointServer(self._runtime, self, handler,
                                graceful_shutdown=graceful_shutdown,
                                metrics_labels=metrics_labels or {})
        await server.start()
        return server

    async def client(self, router_mode: str = "round_robin"):
        """Create a discovering client for this endpoint (reference
        component/client.rs:285 Client + InstanceSource)."""
        from dynamo_tpu.runtime.client import EndpointClient

        client = EndpointClient(self._runtime, self, router_mode=router_mode)
        await client.start()
        return client
