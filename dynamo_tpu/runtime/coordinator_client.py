"""Async client for the control-plane coordinator.

Plays the role of the reference's etcd::Client (lib/runtime/src/transports/
etcd.rs:46-310 — kv_create/kv_put/watch/lease with a primary lease kept alive in
the background) and nats::Client (transports/nats.rs:58-120 — publish/subscribe/
queues/object store) in one connection.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.frame import read_frame, write_frame
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("coordinator_client")


class WatchStream:
    """A prefix watch: initial snapshot + live put/delete events.

    Reference: PrefixWatcher from kv_get_and_watch_prefix (etcd.rs:310)."""

    def __init__(self, client: "CoordinatorClient", watch_id: int,
                 snapshot: list[dict]):
        self._client = client
        self.watch_id = watch_id
        self.snapshot = snapshot
        self.events: asyncio.Queue[dict] = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            yield await self.events.get()

    async def cancel(self) -> None:
        self._client._watches.pop(self.watch_id, None)
        try:
            await self._client._request({"m": "unwatch", "watch_id": self.watch_id})
        except ConnectionError:
            pass


class Subscription:
    """A pub/sub subscription stream (reference: NATS subscribe)."""

    def __init__(self, client: "CoordinatorClient", sub_id: int):
        self._client = client
        self.sub_id = sub_id
        self.messages: asyncio.Queue[dict] = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            yield await self.messages.get()

    async def cancel(self) -> None:
        self._client._subs.pop(self.sub_id, None)
        try:
            await self._client._request({"m": "unsubscribe", "sub": self.sub_id})
        except ConnectionError:
            pass


class CoordinatorClient:
    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, WatchStream] = {}
        self._subs: dict[int, Subscription] = {}
        self._reader_task: asyncio.Task | None = None
        self._keepalive_task: asyncio.Task | None = None
        self._send_lock = asyncio.Lock()
        self.primary_lease_id: int | None = None
        self._lease_ttl_s = 10.0
        self._lease_recreated_callbacks: list = []
        self._regrant_lock = asyncio.Lock()
        self._closed = False

    @classmethod
    async def connect(cls, host: str, port: int, lease_ttl_s: float = 10.0,
                      retries: int = 40, retry_delay: float = 0.25
                      ) -> "CoordinatorClient":
        client = cls(host, port)
        last: Exception | None = None
        for _ in range(retries):
            try:
                client._reader, client._writer = await asyncio.open_connection(host, port)
                break
            except OSError as exc:
                last = exc
                await asyncio.sleep(retry_delay)
        else:
            raise ConnectionError(f"coordinator unreachable at {host}:{port}: {last}")
        client._reader_task = asyncio.create_task(client._read_loop())
        # Primary lease: liveness anchor for everything this process registers
        # (reference: etcd primary lease, transports/etcd/lease.rs).
        client._lease_ttl_s = lease_ttl_s
        client.primary_lease_id = await client.lease_grant(lease_ttl_s)
        client._keepalive_task = asyncio.create_task(
            client._keepalive_loop(client.primary_lease_id, lease_ttl_s / 3))
        return client

    async def close(self, revoke_lease: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if self._keepalive_task:
            self._keepalive_task.cancel()
        if revoke_lease and self.primary_lease_id is not None:
            try:
                await self._request({"m": "lease_revoke", "lease": self.primary_lease_id})
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                msg = await read_frame(self._reader)
                if "i" in msg and msg["i"] is not None and ("ok" in msg):
                    fut = self._pending.pop(msg["i"], None)
                    if fut and not fut.done():
                        if msg["ok"]:
                            fut.set_result(msg.get("r"))
                        else:
                            fut.set_exception(RuntimeError(msg.get("e")))
                elif "w" in msg:
                    watch = self._watches.get(msg["w"])
                    if watch:
                        watch.events.put_nowait(
                            {"event": msg["ev"], "key": msg["k"], "value": msg.get("v")})
                elif "s" in msg:
                    sub = self._subs.get(msg["s"])
                    if sub:
                        sub.messages.put_nowait(
                            {"subject": msg["subject"], "payload": msg["payload"]})
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("coordinator connection lost"))
            self._pending.clear()

    def on_lease_recreated(self, callback) -> None:
        """Register an async callback invoked (with the new lease id) after the
        primary lease had to be re-granted — used by endpoint servers to re-put
        their registrations so a transient stall doesn't silently drain traffic."""
        self._lease_recreated_callbacks.append(callback)

    async def _keepalive_loop(self, lease_id: int, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            try:
                await self._request({"m": "lease_keepalive", "lease": lease_id})
            except ConnectionError:
                log.warning("coordinator connection lost; keepalive stopped")
                return
            except RuntimeError as exc:
                if "not found" not in str(exc):
                    log.warning("lease keepalive error (will retry): %s", exc)
                    continue
                # Lease expired server-side (e.g. event-loop stall past TTL):
                # re-grant and let registrants re-register.
                try:
                    await self._regrant_primary()
                    lease_id = self.primary_lease_id
                except (ConnectionError, RuntimeError) as exc2:
                    log.error("lease re-grant failed: %s", exc2)
                    return

    async def _regrant_primary(self) -> None:
        """Re-grant the primary lease after server-side expiry and replay
        the registration callbacks. Safe under concurrency: whoever loses
        the lock re-checks liveness first."""
        async with self._regrant_lock:
            try:
                await self._request({"m": "lease_keepalive",
                                     "lease": self.primary_lease_id})
                return  # someone else already re-granted
            except RuntimeError:
                pass
            log.error("primary lease %s expired; re-granting",
                      self.primary_lease_id)
            self.primary_lease_id = await self.lease_grant(self._lease_ttl_s)
            for cb in list(self._lease_recreated_callbacks):
                try:
                    await cb(self.primary_lease_id)
                except Exception:  # noqa: BLE001
                    log.exception("lease-recreated callback failed")

    async def _request(self, msg: dict) -> Any:
        if self._writer is None or self._writer.is_closing():
            raise ConnectionError("not connected")
        rid = next(self._ids)
        msg["i"] = rid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            await write_frame(self._writer, msg)
        return await fut

    # -- etcd-shaped API ------------------------------------------------------
    async def lease_grant(self, ttl: float) -> int:
        return await self._request({"m": "lease_grant", "ttl": ttl})

    async def lease_revoke(self, lease_id: int) -> None:
        await self._request({"m": "lease_revoke", "lease": lease_id})

    async def kv_put(self, key: str, value: Any, lease_id: int | None = None,
                     use_primary_lease: bool = False) -> int:
        if use_primary_lease:
            return await self._with_primary_lease(
                lambda lease: self._request(
                    {"m": "kv_put", "k": key, "v": value, "lease": lease}))
        return await self._request({"m": "kv_put", "k": key, "v": value,
                                    "lease": lease_id})

    async def kv_create(self, key: str, value: Any, lease_id: int | None = None,
                        use_primary_lease: bool = False) -> bool:
        """Atomic create; False if the key already exists (etcd.rs kv_create)."""
        if use_primary_lease:
            rev = await self._with_primary_lease(
                lambda lease: self._request(
                    {"m": "kv_create", "k": key, "v": value, "lease": lease}))
        else:
            rev = await self._request({"m": "kv_create", "k": key, "v": value,
                                       "lease": lease_id})
        return rev is not None

    async def _with_primary_lease(self, fn):
        """Run a lease-attached request; if the primary lease expired while
        we weren't looking (event-loop stall past the TTL), re-grant it and
        retry once — registration must not fail just because the process
        was briefly too busy to keep its lease alive."""
        try:
            return await fn(self.primary_lease_id)
        except RuntimeError as exc:
            if "not found" not in str(exc):
                raise
            await self._regrant_primary()
            return await fn(self.primary_lease_id)

    async def kv_get(self, key: str) -> Any | None:
        result = await self._request({"m": "kv_get", "k": key})
        return None if result is None else result["v"]

    async def kv_get_prefix(self, prefix: str) -> list[dict]:
        return await self._request({"m": "kv_get_prefix", "k": prefix})

    async def kv_delete(self, key: str) -> bool:
        return await self._request({"m": "kv_delete", "k": key})

    async def kv_delete_prefix(self, prefix: str) -> int:
        return await self._request({"m": "kv_delete_prefix", "k": prefix})

    async def watch_prefix(self, prefix: str) -> WatchStream:
        # Client allocates the watch id and registers the stream BEFORE the
        # request, so events racing the watch response are never dropped.
        wid = next(self._ids)
        watch = WatchStream(self, wid, [])
        self._watches[wid] = watch
        try:
            result = await self._request({"m": "watch", "k": prefix, "wid": wid})
        except BaseException:
            self._watches.pop(wid, None)
            raise
        watch.snapshot = result["snapshot"]
        return watch

    # -- NATS-shaped API ------------------------------------------------------
    async def publish(self, subject: str, payload: Any) -> None:
        await self._request({"m": "publish", "subject": subject, "payload": payload})

    async def subscribe(self, subject: str) -> Subscription:
        sid = next(self._ids)
        sub = Subscription(self, sid)
        self._subs[sid] = sub
        try:
            await self._request({"m": "subscribe", "subject": subject, "sid": sid})
        except BaseException:
            self._subs.pop(sid, None)
            raise
        return sub

    async def queue_push(self, queue: str, item: Any) -> None:
        await self._request({"m": "queue_push", "queue": queue, "item": item})

    async def queue_pop(self, queue: str, timeout: float = 0.0) -> Any | None:
        result = await self._request(
            {"m": "queue_pop", "queue": queue, "timeout": timeout})
        return None if result is None else result["item"]

    async def queue_len(self, queue: str) -> int:
        return await self._request({"m": "queue_len", "queue": queue})

    async def object_put(self, key: str, data: bytes) -> None:
        await self._request({"m": "object_put", "k": key, "v": data})

    async def object_get(self, key: str) -> bytes | None:
        return await self._request({"m": "object_get", "k": key})
