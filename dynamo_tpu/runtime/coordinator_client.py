"""Async client for the control-plane coordinator.

Plays the role of the reference's etcd::Client (lib/runtime/src/transports/
etcd.rs:46-310 — kv_create/kv_put/watch/lease with a primary lease kept alive in
the background) and nats::Client (transports/nats.rs:58-120 — publish/subscribe/
queues/object store) in one connection.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import chaos
from dynamo_tpu.runtime.frame import read_frame, write_frame
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.retry import Backoff, RetryPolicy, policies

log = get_logger("coordinator_client")


class WatchStream:
    """A prefix watch: initial snapshot + live put/delete events.

    Reference: PrefixWatcher from kv_get_and_watch_prefix (etcd.rs:310)."""

    def __init__(self, client: "CoordinatorClient", watch_id: int,
                 snapshot: list[dict], prefix: str = ""):
        self._client = client
        self.watch_id = watch_id
        self.snapshot = snapshot
        self.prefix = prefix
        # Watch deltas must never be dropped (a lost DELETE strands a
        # dead instance in discovery forever); volume is bounded by
        # actual cluster-state churn, not request traffic.
        # dtpu: ignore[unbounded-queue] -- lossless-by-contract control stream
        self.events: asyncio.Queue[dict] = asyncio.Queue()
        # Keys this watch has reported as present — lets a reconnect
        # synthesize DELETE events for keys that vanished with the old
        # coordinator (consumers like instance discovery only remove
        # entries on deletes).
        self.known_keys: set[str] = {item["k"] for item in snapshot}
        # While a reconnect replays the snapshot, live events buffer here
        # so a pre-replay put can't be overwritten by the older snapshot.
        self.paused = False
        self._buffer: list[dict] = []

    def deliver(self, event: dict) -> None:
        if event["event"] == "put":
            self.known_keys.add(event["key"])
        else:
            self.known_keys.discard(event["key"])
        if self.paused:
            self._buffer.append(event)
        else:
            self.events.put_nowait(event)

    def flush(self) -> None:
        self.paused = False
        for ev in self._buffer:
            # Re-apply to known_keys: a reconnect replay overwrites the
            # set from its snapshot, which predates these buffered events.
            if ev["event"] == "put":
                self.known_keys.add(ev["key"])
            else:
                self.known_keys.discard(ev["key"])
            self.events.put_nowait(ev)
        self._buffer.clear()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            yield await self.events.get()

    async def cancel(self) -> None:
        self._client._watches.pop(self.watch_id, None)
        try:
            await self._client._request({"m": "unwatch", "watch_id": self.watch_id})
        except ConnectionError:
            pass


class Subscription:
    """A pub/sub subscription stream (reference: NATS subscribe)."""

    def __init__(self, client: "CoordinatorClient", sub_id: int,
                 subject: str = ""):
        self._client = client
        self.sub_id = sub_id
        self.subject = subject
        # Control-plane pubsub: volume bounded by cluster churn
        # (KV events/metrics), not user traffic.
        # dtpu: ignore[unbounded-queue] -- see above
        self.messages: asyncio.Queue[dict] = asyncio.Queue()

    def __aiter__(self) -> AsyncIterator[dict]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[dict]:
        while True:
            yield await self.messages.get()

    async def cancel(self) -> None:
        self._client._subs.pop(self.sub_id, None)
        try:
            await self._client._request({"m": "unsubscribe", "sub": self.sub_id})
        except ConnectionError:
            pass


class CoordinatorClient:
    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, WatchStream] = {}
        self._subs: dict[int, Subscription] = {}
        self._reader_task: asyncio.Task | None = None
        self._keepalive_task: asyncio.Task | None = None
        self._reconnect_task: asyncio.Task | None = None
        self._send_lock = asyncio.Lock()
        self.primary_lease_id: int | None = None
        self._lease_ttl_s = 10.0
        self._lease_recreated_callbacks: list = []
        self._regrant_lock = asyncio.Lock()
        self._closed = False
        # False between a detected disconnect and a completed reconnect:
        # _request fails fast instead of writing into a dead socket whose
        # reply future nobody would ever resolve.
        self._connected = True

    @classmethod
    async def connect(cls, host: str, port: int, lease_ttl_s: float = 10.0,
                      retries: int = 40, retry_delay: float = 0.25
                      ) -> "CoordinatorClient":
        client = cls(host, port)
        last: Exception | None = None
        policy = policies.COORD_CONNECT
        if (retries, retry_delay) != (40, 0.25):  # caller override
            policy = RetryPolicy(initial_delay_s=retry_delay,
                                 max_delay_s=policy.max_delay_s,
                                 multiplier=policy.multiplier,
                                 jitter=policy.jitter, max_attempts=retries)
        backoff = Backoff(policy)
        while True:
            try:
                client._reader, client._writer = await asyncio.open_connection(host, port)
                break
            except OSError as exc:
                last = exc
                if not await backoff.sleep():
                    raise ConnectionError(
                        f"coordinator unreachable at {host}:{port}: {last}")
        client._reader_task = asyncio.create_task(client._read_loop())
        # Primary lease: liveness anchor for everything this process registers
        # (reference: etcd primary lease, transports/etcd/lease.rs).
        client._lease_ttl_s = lease_ttl_s
        client.primary_lease_id = await client.lease_grant(lease_ttl_s)
        client._keepalive_task = asyncio.create_task(
            client._keepalive_loop(client.primary_lease_id, lease_ttl_s / 3))
        return client

    async def close(self, revoke_lease: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if self._keepalive_task:
            self._keepalive_task.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if revoke_lease and self.primary_lease_id is not None:
            try:
                await self._request({"m": "lease_revoke", "lease": self.primary_lease_id})
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                msg = await read_frame(self._reader,
                                       chaos_site="coord_client")
                if "i" in msg and msg["i"] is not None and ("ok" in msg):
                    fut = self._pending.pop(msg["i"], None)
                    if fut and not fut.done():
                        if msg["ok"]:
                            fut.set_result(msg.get("r"))
                        else:
                            fut.set_exception(RuntimeError(msg.get("e")))
                elif "w" in msg:
                    watch = self._watches.get(msg["w"])
                    if watch:
                        watch.deliver(
                            {"event": msg["ev"], "key": msg["k"], "value": msg.get("v")})
                elif "s" in msg:
                    sub = self._subs.get(msg["s"])
                    if sub:
                        sub.messages.put_nowait(
                            {"subject": msg["subject"], "payload": msg["payload"]})
        except asyncio.CancelledError:
            self._connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("coordinator connection lost"))
            self._pending.clear()
        except Exception:  # noqa: BLE001 — ANY read failure is a disconnect
            # (ConnectionError subclasses, plain OSError like ETIMEDOUT,
            # or a corrupt-frame decode error).
            self._connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("coordinator connection lost"))
            self._pending.clear()
            if not self._closed:
                # Coordinator went away (restart/crash): reconnect in the
                # background and rebuild this client's server-side state.
                self._reconnect_task = asyncio.ensure_future(
                    self._reconnect())

    async def _reconnect(self) -> None:
        """Survive a coordinator restart: redial (forever, with capped
        jittered backoff from policies.COORD_RECONNECT, until closed),
        re-grant the primary lease, replay registrations (lease-recreated
        callbacks), and re-establish every live watch and subscription —
        synthesizing DELETE events for keys that vanished with the old
        coordinator. Server-side queue contents do not survive (stated
        posture: the coordinator is a restartable but non-persistent
        control plane)."""
        if self._keepalive_task:
            self._keepalive_task.cancel()
        log.warning("coordinator connection lost; reconnecting to %s:%d",
                    self.host, self.port)
        backoff = Backoff(policies.COORD_RECONNECT)
        while not self._closed:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port)
                break
            except OSError:
                await backoff.sleep()
        if self._closed:
            return
        # Fail anything that slipped into the pending map while the old
        # socket was dying, then open for business on the new one.
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("coordinator connection lost"))
        self._pending.clear()
        self._reader_task = asyncio.create_task(self._read_loop())
        self._connected = True
        try:
            self.primary_lease_id = await self.lease_grant(self._lease_ttl_s)
            self._keepalive_task = asyncio.create_task(
                self._keepalive_loop(self.primary_lease_id,
                                     self._lease_ttl_s / 3))
            # Re-establish watches first so replayed registrations (ours and
            # other clients') flow into them as put events. Live events
            # buffer while each watch's snapshot replays, so a fresh put
            # can't be clobbered by the older snapshot value.
            for watch in list(self._watches.values()):
                watch.paused = True
                result = await self._request(
                    {"m": "watch", "k": watch.prefix, "wid": watch.watch_id})
                new_keys = {item["k"] for item in result["snapshot"]}
                for key in sorted(watch.known_keys - new_keys):
                    watch.events.put_nowait(
                        {"event": "delete", "key": key, "value": None})
                for item in result["snapshot"]:
                    watch.events.put_nowait(
                        {"event": "put", "key": item["k"],
                         "value": item["v"]})
                watch.known_keys = new_keys
                watch.flush()
            for sub in list(self._subs.values()):
                await self._request({"m": "subscribe", "subject": sub.subject,
                                     "sid": sub.sub_id})
            for cb in list(self._lease_recreated_callbacks):
                try:
                    await cb(self.primary_lease_id)
                except Exception:  # noqa: BLE001
                    log.exception("reconnect registration replay failed")
            log.info("coordinator reconnected; state replayed "
                     "(%d watches, %d subs, %d registrations)",
                     len(self._watches), len(self._subs),
                     len(self._lease_recreated_callbacks))
        except Exception:  # noqa: BLE001
            # Replay failed (server rejected or died again): force the read
            # loop down so the disconnect path schedules a fresh reconnect
            # — a half-replayed client must not linger looking healthy.
            log.exception("reconnect state replay failed; forcing redial")
            for watch in list(self._watches.values()):
                watch.flush()
            if self._writer is not None:
                self._writer.close()

    def on_lease_recreated(self, callback) -> None:
        """Register an async callback invoked (with the new lease id) after the
        primary lease had to be re-granted — used by endpoint servers to re-put
        their registrations so a transient stall doesn't silently drain traffic."""
        self._lease_recreated_callbacks.append(callback)

    async def _keepalive_loop(self, lease_id: int, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            if chaos.ACTIVE and chaos.fire("lease.starve"):
                # Injected keepalive starvation: sleep past the TTL so
                # the server expires the lease, then resume — the next
                # keepalive's "not found" exercises the regrant path.
                log.warning("chaos: starving lease %d keepalives", lease_id)
                await asyncio.sleep(self._lease_ttl_s * 1.5)
                continue
            try:
                await self._request({"m": "lease_keepalive", "lease": lease_id})
            except ConnectionError:
                # The read loop schedules the reconnect (which restarts a
                # fresh keepalive task); this one just winds down.
                log.warning("coordinator connection lost; keepalive stopped")
                return
            except RuntimeError as exc:
                if "not found" not in str(exc):
                    log.warning("lease keepalive error (will retry): %s", exc)
                    continue
                # Lease expired server-side (e.g. event-loop stall past TTL):
                # re-grant and let registrants re-register.
                try:
                    await self._regrant_primary()
                    lease_id = self.primary_lease_id
                except (ConnectionError, RuntimeError) as exc2:
                    log.error("lease re-grant failed: %s", exc2)
                    return

    async def _regrant_primary(self) -> None:
        """Re-grant the primary lease after server-side expiry and replay
        the registration callbacks. Safe under concurrency: whoever loses
        the lock re-checks liveness first."""
        async with self._regrant_lock:
            try:
                await self._request({"m": "lease_keepalive",
                                     "lease": self.primary_lease_id})
                return  # someone else already re-granted
            except RuntimeError:
                pass
            log.error("primary lease %s expired; re-granting",
                      self.primary_lease_id)
            self.primary_lease_id = await self.lease_grant(self._lease_ttl_s)
            for cb in list(self._lease_recreated_callbacks):
                try:
                    await cb(self.primary_lease_id)
                except Exception:  # noqa: BLE001
                    log.exception("lease-recreated callback failed")

    # Hard ceiling on any single control-plane round trip. Ops complete
    # in milliseconds when the coordinator is healthy; one that can't
    # answer within this deadline is indistinguishable from a
    # partitioned one, so the reply wait must not be unbounded (a lost
    # reply frame would otherwise park the caller forever).
    REQUEST_TIMEOUT_S = 30.0

    async def _request(self, msg: dict, timeout: float | None = None) -> Any:
        if (self._writer is None or self._writer.is_closing()
                or not self._connected):
            raise ConnectionError("not connected")
        rid = next(self._ids)
        msg["i"] = rid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            await write_frame(self._writer, msg, chaos_site="coord_client")
        try:
            return await asyncio.wait_for(
                fut, self.REQUEST_TIMEOUT_S if timeout is None else timeout)
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            # Force the connection down so the read loop schedules a
            # reconnect — a silently unresponsive control plane must be
            # treated exactly like a dead one.
            if self._writer is not None and not self._closed:
                self._writer.close()
            raise ConnectionError(
                f"coordinator request {msg.get('m')!r} timed out") from None

    # -- etcd-shaped API ------------------------------------------------------
    async def lease_grant(self, ttl: float) -> int:
        return await self._request({"m": "lease_grant", "ttl": ttl})

    async def lease_revoke(self, lease_id: int) -> None:
        await self._request({"m": "lease_revoke", "lease": lease_id})

    async def kv_put(self, key: str, value: Any, lease_id: int | None = None,
                     use_primary_lease: bool = False) -> int:
        if use_primary_lease:
            return await self._with_primary_lease(
                lambda lease: self._request(
                    {"m": "kv_put", "k": key, "v": value, "lease": lease}))
        return await self._request({"m": "kv_put", "k": key, "v": value,
                                    "lease": lease_id})

    async def kv_create(self, key: str, value: Any, lease_id: int | None = None,
                        use_primary_lease: bool = False) -> bool:
        """Atomic create; False if the key already exists (etcd.rs kv_create)."""
        if use_primary_lease:
            rev = await self._with_primary_lease(
                lambda lease: self._request(
                    {"m": "kv_create", "k": key, "v": value, "lease": lease}))
        else:
            rev = await self._request({"m": "kv_create", "k": key, "v": value,
                                       "lease": lease_id})
        return rev is not None

    async def _with_primary_lease(self, fn):
        """Run a lease-attached request; if the primary lease expired while
        we weren't looking (event-loop stall past the TTL), re-grant it and
        retry once — registration must not fail just because the process
        was briefly too busy to keep its lease alive."""
        try:
            return await fn(self.primary_lease_id)
        except RuntimeError as exc:
            if "not found" not in str(exc):
                raise
            await self._regrant_primary()
            return await fn(self.primary_lease_id)

    async def kv_get(self, key: str) -> Any | None:
        result = await self._request({"m": "kv_get", "k": key})
        return None if result is None else result["v"]

    async def kv_get_prefix(self, prefix: str) -> list[dict]:
        return await self._request({"m": "kv_get_prefix", "k": prefix})

    async def kv_delete(self, key: str) -> bool:
        return await self._request({"m": "kv_delete", "k": key})

    async def kv_delete_prefix(self, prefix: str) -> int:
        return await self._request({"m": "kv_delete_prefix", "k": prefix})

    async def watch_prefix(self, prefix: str) -> WatchStream:
        # Client allocates the watch id and registers the stream BEFORE the
        # request, so events racing the watch response are never dropped.
        wid = next(self._ids)
        watch = WatchStream(self, wid, [], prefix=prefix)
        self._watches[wid] = watch
        try:
            result = await self._request({"m": "watch", "k": prefix, "wid": wid})
        except BaseException:
            self._watches.pop(wid, None)
            raise
        watch.snapshot = result["snapshot"]
        watch.known_keys = {item["k"] for item in watch.snapshot}
        return watch

    # -- NATS-shaped API ------------------------------------------------------
    async def publish(self, subject: str, payload: Any) -> None:
        await self._request({"m": "publish", "subject": subject, "payload": payload})

    async def subscribe(self, subject: str) -> Subscription:
        sid = next(self._ids)
        sub = Subscription(self, sid, subject=subject)
        self._subs[sid] = sub
        try:
            await self._request({"m": "subscribe", "subject": subject, "sid": sid})
        except BaseException:
            self._subs.pop(sid, None)
            raise
        return sub

    async def queue_push(self, queue: str, item: Any) -> None:
        await self._request({"m": "queue_push", "queue": queue, "item": item})

    async def queue_pop(self, queue: str, timeout: float = 0.0) -> Any | None:
        if chaos.ACTIVE and chaos.fire("queue.pop_error"):
            raise ConnectionError("chaos: injected queue_pop failure")
        # The server blocks up to ``timeout`` before answering None, so
        # the wire deadline must sit beyond it.
        result = await self._request(
            {"m": "queue_pop", "queue": queue, "timeout": timeout},
            timeout=timeout + self.REQUEST_TIMEOUT_S)
        return None if result is None else result["item"]

    async def queue_len(self, queue: str) -> int:
        return await self._request({"m": "queue_len", "queue": queue})

    async def object_put(self, key: str, data: bytes) -> None:
        await self._request({"m": "object_put", "k": key, "v": data})

    async def object_get(self, key: str) -> bytes | None:
        return await self._request({"m": "object_get", "k": key})
