"""Overload defense: adaptive admission, deadline-aware shedding,
priority classes, per-worker circuit breakers, and brownout degradation.

The reference Dynamo treats overload as an SLA-governed, planner-managed
condition (WorkerMonitor busy detection + planner scaling); this module is
the in-process half of that story — what a frontend does in the seconds
before new capacity exists. Four cooperating pieces:

- ``AdaptiveLimiter`` — an AIMD concurrency limiter wrapped around
  frontend request handling. The limit grows additively while observed
  per-phase latency (TTFT for streaming) stays under
  ``target_latency_ms`` and shrinks multiplicatively when it doesn't,
  so admitted requests stay fast no matter the offered load. Excess
  arrivals wait in a bounded queue; everything past the bound is shed
  with a typed, retryable 503.

- **Deadline-aware shedding** — a request carrying a client deadline
  (``x-request-deadline-ms``, or the server default) is rejected the
  moment the admission-queue projection says the deadline cannot be
  met, instead of timing out after consuming a slot. Deadline sheds are
  client-pacing rejections (``RateLimitedError`` → HTTP 429): retrying
  immediately with the same deadline cannot succeed.

- **Priority classes** — ``interactive`` sheds last and is granted
  queued slots first; ``batch`` sheds outright once pressure reaches
  ``batch_shed_level`` and can never starve interactive waiters.

- ``CircuitBreaker`` / ``BreakerBoard`` — per-worker failure tracking
  in the router/client path. Consecutive typed failures or latency
  outliers open the breaker; the scheduler excludes that instance;
  after ``breaker_cooldown_s`` a half-open probe re-admits it.

Brownout: ``pressure_level()`` (0..3) drives degradation hooks — batch
shedding, ``clamp_max_tokens`` — and is reported to clients in the
``X-Overload-Brownout`` response header. The TPU engine runs its own
engine-local brownout off its TTFT projection (engine/engine.py).

Determinism: nothing here reads a wall clock it wasn't given (``clock``
is injectable) and the only RNG (Retry-After jitter, which de-syncs
client retry herds) is seeded from ``OverloadConfig.seed`` — the unit
matrix in tests/test_overload.py drives everything from a fake clock.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import random
import time
from typing import Callable, Iterable

from dynamo_tpu.runtime import journal
from dynamo_tpu.runtime.errors import OverloadedError, RateLimitedError
from dynamo_tpu.runtime.journal import EventKind
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("overload")

#: Journal throttle for shed events: an overload storm sheds thousands
#: of requests per second — the decision plane wants one event per
#: (reason, priority) per interval with a suppressed count, not all of
#: them (the shed_total counter keeps the exact tally).
_SHED_JOURNAL_INTERVAL_S = 1.0

PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"
PRIORITIES = (PRIORITY_INTERACTIVE, PRIORITY_BATCH)

# Breaker states (exposed via BreakerBoard.state for metrics/tests).
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


@dataclasses.dataclass
class OverloadConfig:
    """Knobs for the whole defense stack. All plain scalars so the
    generic DTPU_OVERLOAD_* env override in runtime/config.py can map
    them 1:1 (0 = disabled where a feature is optional)."""

    enabled: bool = True

    # -- adaptive admission (AIMD on observed latency vs. target) ------------
    # Per-phase latency target the limit adapts against: time from
    # admission to first token for streaming routes.
    target_latency_ms: float = 5000.0
    min_concurrency: int = 1
    max_concurrency: int = 512
    initial_concurrency: int = 16
    # Classic AIMD: +additive/limit per under-target completion (≈ +additive
    # per RTT), ×multiplicative on an over-target completion, at most one
    # decrease per decrease_cooldown_s so a burst of stale completions
    # can't collapse the limit to the floor in one tick.
    additive_increase: float = 1.0
    multiplicative_decrease: float = 0.7
    decrease_cooldown_s: float = 1.0
    # Bounded admission wait queue (all priorities combined).
    queue_depth: int = 64
    # Server default when the client sends no x-request-deadline-ms.
    default_deadline_ms: float = 30_000.0

    # -- priority / brownout --------------------------------------------------
    # pressure_level() thresholds: level1 = saturated, level2/3 = queue
    # filling. pressure = inflight/limit while the queue is empty, else
    # 1 + waiting/queue_depth.
    level1_pressure: float = 0.95
    level2_pressure: float = 1.25
    level3_pressure: float = 1.75
    # Batch traffic sheds outright at this pressure level (interactive
    # only sheds via queue bound / deadline projection).
    batch_shed_level: int = 2
    # Brownout degradation: at >= clamp level, responses are clamped to
    # brownout_max_tokens (0 disables clamping).
    brownout_clamp_level: int = 2
    brownout_max_tokens: int = 0

    # -- Retry-After derivation ----------------------------------------------
    # Fallback when the limiter has no calibrated service time yet (and
    # the config default the HTTP layer uses for non-limiter 503s).
    retry_after_default_s: float = 1.0
    retry_after_max_s: float = 30.0

    # -- per-worker circuit breakers -----------------------------------------
    breaker_enabled: bool = True
    breaker_failures: int = 5        # consecutive failures/outliers to open
    breaker_cooldown_s: float = 5.0  # open -> half-open probe delay
    # A completion slower than factor x the worker's EWMA latency counts
    # as an outlier failure (only once min_samples calibrated the EWMA).
    breaker_latency_factor: float = 5.0
    breaker_min_samples: int = 20

    # Seeds the Retry-After jitter stream (the only randomness here).
    seed: int = 0


# -- adaptive admission -------------------------------------------------------


class _Waiter:
    __slots__ = ("fut", "priority", "enqueued_t")

    def __init__(self, fut: asyncio.Future, priority: str, enqueued_t: float):
        self.fut = fut
        self.priority = priority
        self.enqueued_t = enqueued_t


class Permit:
    """One admitted request. Use as a context manager; call
    ``note_latency`` when the request's phase latency (TTFT) is known —
    that sample is what AIMD adapts the limit against."""

    __slots__ = ("_limiter", "priority", "admitted_t", "latency_s",
                 "_released")

    def __init__(self, limiter: "AdaptiveLimiter", priority: str,
                 admitted_t: float):
        self._limiter = limiter
        self.priority = priority
        self.admitted_t = admitted_t
        self.latency_s: float | None = None
        self._released = False

    def note_latency(self, seconds: float) -> None:
        if self.latency_s is None:
            self.latency_s = seconds

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._limiter._release(self)

    def __enter__(self) -> "Permit":
        return self

    def __exit__(self, *_exc) -> None:
        self.release()


class AdaptiveLimiter:
    """AIMD concurrency limiter + bounded priority wait queue +
    deadline-aware shedding + brownout pressure signal.

    ``admit()`` returns a ``Permit`` or raises:

    - ``RateLimitedError`` (HTTP 429, not retryable as-is): the deadline
      cannot be met by the queue projection, the wait outlived the
      deadline, or batch traffic hit the brownout shed level.
    - ``OverloadedError`` (HTTP 503, retryable): the bounded wait queue
      is full — pure capacity, try again after Retry-After.
    """

    def __init__(self, config: OverloadConfig | None = None, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or OverloadConfig()
        self._clock = clock
        self.limit = float(self.cfg.initial_concurrency)
        self.inflight = 0
        self._queues: dict[str, collections.deque[_Waiter]] = {
            p: collections.deque() for p in PRIORITIES}
        # EWMA of observed per-phase latency; the admission-queue
        # projection and Retry-After both derive from it. None until the
        # first sample — projections are conservative (never shed on an
        # uncalibrated clock).
        self.avg_service_s: float | None = None
        self._last_decrease_t = -1e18
        self._rng = random.Random(f"{self.cfg.seed}:overload")
        # Local mirrors of the metrics (always available to tests).
        self.admitted_total = collections.Counter()   # priority -> n
        self.shed_counts = collections.Counter()      # (reason, priority)
        # Journal state: shed-event throttle + last brownout level.
        self._shed_journal: dict[tuple[str, str], list] = {}
        self._journal_level = 0
        self._m_shed = self._m_admitted = None
        self._m_limit = self._m_queue = self._m_level = None
        if metrics is not None:
            m = metrics.namespace("overload")
            self._m_shed = m.counter(
                "shed_total", "Requests shed by the overload defense",
                ["reason", "priority"])
            self._m_admitted = m.counter(
                "admitted_total", "Requests admitted past the limiter",
                ["priority"])
            self._m_limit = m.gauge(
                "concurrency_limit", "Current AIMD concurrency limit")
            self._m_queue = m.gauge(
                "admission_queue_depth", "Requests waiting for admission")
            self._m_level = m.gauge(
                "brownout_level", "Current brownout pressure level")
            self._m_limit.set(self.limit)

    # -- pressure / projections -----------------------------------------------
    def waiting(self) -> int:
        return sum(1 for q in self._queues.values()
                   for w in q if not w.fut.done())

    def pressure(self) -> float:
        """< 1 while slots are free; 1 + queue fraction once saturated."""
        waiting = self.waiting()
        if waiting:
            return 1.0 + waiting / max(1, self.cfg.queue_depth)
        return self.inflight / max(1.0, self.limit)

    def pressure_level(self) -> int:
        p = self.pressure()
        cfg = self.cfg
        level = (0 if p < cfg.level1_pressure else
                 1 if p < cfg.level2_pressure else
                 2 if p < cfg.level3_pressure else 3)
        if self._m_level is not None:
            self._m_level.set(level)
        if level != self._journal_level:
            # Brownout edges are rare and load-bearing (they gate batch
            # shedding and token clamping): every change is journaled.
            journal.emit(EventKind.BROWNOUT_CHANGE,
                         **{"from": self._journal_level, "to": level,
                            "pressure": round(p, 3)})
            self._journal_level = level
        return level

    def projected_wait_s(self, position: int) -> float:
        """Time until a new arrival at queue ``position`` would get a
        slot, from the calibrated service time. 0 until calibrated —
        never shed on a projection the limiter can't back up."""
        if not self.avg_service_s:
            return 0.0
        return (position + 1) * self.avg_service_s / max(1.0, self.limit)

    def retry_after_s(self) -> float:
        """Retry-After for shed responses: the queue-drain projection
        (or the config default before calibration), jittered ±20% from
        the seeded stream so shed clients don't return in lockstep."""
        base = (self.projected_wait_s(self.waiting())
                or self.cfg.retry_after_default_s)
        base *= 1.0 + 0.2 * (2.0 * self._rng.random() - 1.0)
        return max(0.1, min(self.cfg.retry_after_max_s, base))

    def clamp_max_tokens(self, requested: int | None) -> int | None:
        """Brownout hook: the max_tokens to apply, or None to leave the
        request alone."""
        cfg = self.cfg
        if (not cfg.brownout_max_tokens
                or self.pressure_level() < cfg.brownout_clamp_level):
            return None
        if requested is not None and requested <= cfg.brownout_max_tokens:
            return None
        return cfg.brownout_max_tokens

    # -- admission ------------------------------------------------------------
    async def admit(self, priority: str = PRIORITY_INTERACTIVE,
                    deadline_ms: float | None = None) -> Permit:
        if priority not in self._queues:
            priority = PRIORITY_INTERACTIVE
        cfg = self.cfg
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        if (priority == PRIORITY_BATCH
                and self.pressure_level() >= cfg.batch_shed_level):
            raise self._shed(
                "priority", priority,
                RateLimitedError(
                    "batch traffic shed under brownout "
                    f"(pressure level {self.pressure_level()})",
                    retry_after_s=self.retry_after_s()))
        if self.inflight < int(self.limit):
            return self._grant(priority)
        waiting = self.waiting()
        if waiting >= cfg.queue_depth:
            raise self._shed(
                "queue_full", priority,
                OverloadedError(
                    f"admission queue full ({waiting} waiting, "
                    f"limit {int(self.limit)})",
                    retry_after_s=self.retry_after_s()))
        projected = self.projected_wait_s(waiting)
        if projected * 1000.0 > deadline_ms:
            raise self._shed(
                "deadline", priority,
                RateLimitedError(
                    f"deadline {deadline_ms:.0f} ms infeasible: projected "
                    f"admission wait {projected * 1000.0:.0f} ms "
                    f"({waiting} ahead at limit {int(self.limit)})",
                    retry_after_s=self.retry_after_s()))
        waiter = _Waiter(asyncio.get_running_loop().create_future(),
                         priority, self._clock())
        self._queues[priority].append(waiter)
        if self._m_queue is not None:
            self._m_queue.set(self.waiting())
        try:
            await asyncio.wait_for(waiter.fut, deadline_ms / 1000.0)
        except asyncio.TimeoutError:
            raise self._shed(
                "deadline_wait", priority,
                RateLimitedError(
                    f"deadline {deadline_ms:.0f} ms expired while waiting "
                    "for admission",
                    retry_after_s=self.retry_after_s())) from None
        except asyncio.CancelledError:
            # Caller vanished mid-wait (client disconnect): if the
            # wakeup already transferred a slot to us, hand it back —
            # otherwise the slot leaks and capacity shrinks forever.
            if waiter.fut.done() and not waiter.fut.cancelled():
                self.inflight -= 1
                self._wake_waiters()
            raise
        finally:
            try:
                self._queues[priority].remove(waiter)
            except ValueError:
                pass
            if self._m_queue is not None:
                self._m_queue.set(self.waiting())
        # Granted: _wake_waiters already took the inflight slot for us.
        return self._grant(priority, counted=True)

    def _grant(self, priority: str, counted: bool = False) -> Permit:
        if not counted:
            self.inflight += 1
        self.admitted_total[priority] += 1
        if self._m_admitted is not None:
            self._m_admitted.inc(priority=priority)
        return Permit(self, priority, self._clock())

    def _shed(self, reason: str, priority: str, exc: Exception) -> Exception:
        self.shed_counts[(reason, priority)] += 1
        if self._m_shed is not None:
            self._m_shed.inc(reason=reason, priority=priority)
        # Decision plane: one typed shed event per (reason, priority)
        # per throttle interval, carrying how many siblings it speaks
        # for. Cause: the brownout edge when one is active (priority
        # sheds ARE the brownout acting), else root.
        now = self._clock()
        state = self._shed_journal.setdefault((reason, priority), [-1e18, 0])
        if now - state[0] >= _SHED_JOURNAL_INTERVAL_S:
            suppressed, state[0], state[1] = state[1], now, 0
            cause = (journal.recent_ref(EventKind.BROWNOUT_CHANGE)
                     if reason == "priority" else None)
            journal.emit(EventKind.SHED, cause=cause, reason=reason,
                         priority=priority, limit=int(self.limit),
                         waiting=self.waiting(), suppressed=suppressed)
        else:
            state[1] += 1
        # The typed reason rides the exception so the accounting stream
        # (llm/recorder.py RequestLedger) records WHY, not just that a
        # 429/503 happened.
        exc.shed_reason = reason
        return exc

    # -- release / AIMD -------------------------------------------------------
    def _release(self, permit: Permit) -> None:
        self.inflight -= 1
        if permit.latency_s is not None:
            self._observe(permit.latency_s)
        self._wake_waiters()

    def _observe(self, latency_s: float) -> None:
        cfg = self.cfg
        self.avg_service_s = (
            latency_s if self.avg_service_s is None
            else 0.8 * self.avg_service_s + 0.2 * latency_s)
        if latency_s * 1000.0 > cfg.target_latency_ms:
            now = self._clock()
            if now - self._last_decrease_t >= cfg.decrease_cooldown_s:
                self._last_decrease_t = now
                self.limit = max(float(cfg.min_concurrency),
                                 self.limit * cfg.multiplicative_decrease)
        else:
            self.limit = min(float(cfg.max_concurrency),
                             self.limit + cfg.additive_increase
                             / max(1.0, self.limit))
        if self._m_limit is not None:
            self._m_limit.set(self.limit)

    def _wake_waiters(self) -> None:
        """Hand freed slots to waiters — interactive strictly first, so
        batch can never starve interactive under brownout."""
        while self.inflight < int(self.limit):
            waiter = None
            for priority in PRIORITIES:
                q = self._queues[priority]
                while q:
                    w = q[0]
                    if w.fut.done():   # timed out / cancelled: discard
                        q.popleft()
                        continue
                    waiter = w
                    break
                if waiter is not None:
                    break
            if waiter is None:
                return
            self._queues[waiter.priority].popleft()
            self.inflight += 1     # the slot transfers with the wakeup
            waiter.fut.set_result(None)


# -- per-worker circuit breakers ----------------------------------------------


class CircuitBreaker:
    """closed -> open -> half-open state machine for one worker.

    ``record_failure`` on consecutive typed failures (or latency
    outliers vs. the worker's own EWMA) opens the breaker;
    ``allows()`` turns false until ``breaker_cooldown_s`` elapses, then
    a single half-open probe is admitted (``on_dispatch`` marks it in
    flight). Probe success closes the breaker; probe failure re-opens
    it for another cooldown."""

    __slots__ = ("cfg", "_clock", "state", "streak", "opened_t",
                 "ewma_latency_s", "samples", "probe_inflight", "opens",
                 "probation")

    def __init__(self, cfg: OverloadConfig,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self.state = CLOSED
        self.streak = 0          # consecutive failures + outliers
        self.opened_t = 0.0
        self.ewma_latency_s: float | None = None
        self.samples = 0
        self.probe_inflight = False
        self.opens = 0           # total open transitions (observability)
        # Canary-gated join (llm/canary.py): a held breaker admits NO
        # traffic — not even the post-cooldown half-open probe — until
        # a success (the canary's, via direct routing) releases it.
        self.probation = False

    def allows(self) -> bool:
        if self.probation:
            return False
        if not self.cfg.breaker_enabled or self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - self.opened_t < self.cfg.breaker_cooldown_s:
                return False
            self.state = HALF_OPEN
            self.probe_inflight = False
        return not self.probe_inflight

    def on_dispatch(self) -> None:
        if self.state == HALF_OPEN:
            self.probe_inflight = True

    def record_success(self, latency_s: float | None = None) -> None:
        self.probation = False
        if self.state in (HALF_OPEN, OPEN):
            # Probe (or a straggler from before the open) succeeded:
            # close and forget the episode.
            self.state = CLOSED
            self.probe_inflight = False
            self.streak = 0
            return
        outlier = (latency_s is not None
                   and self.ewma_latency_s is not None
                   and self.samples >= self.cfg.breaker_min_samples
                   and latency_s > self.cfg.breaker_latency_factor
                   * self.ewma_latency_s)
        if latency_s is not None:
            self.ewma_latency_s = (
                latency_s if self.ewma_latency_s is None
                else 0.9 * self.ewma_latency_s + 0.1 * latency_s)
            self.samples += 1
        if outlier:
            self.streak += 1
            self._maybe_open()
        else:
            self.streak = 0

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self._open()
            return
        self.streak += 1
        self._maybe_open()

    def _maybe_open(self) -> None:
        if self.state == CLOSED and self.streak >= self.cfg.breaker_failures:
            self._open()

    def _open(self) -> None:
        self.state = OPEN
        self.opened_t = self._clock()
        self.probe_inflight = False
        self.opens += 1


class BreakerBoard:
    """Per-worker breakers for one client/endpoint. The request-plane
    client records outcomes; the scheduler/router asks ``admitted()``
    to exclude open instances."""

    def __init__(self, config: OverloadConfig | None = None, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or OverloadConfig()
        self._clock = clock
        self._breakers: dict[int, CircuitBreaker] = {}
        self._m_state = self._m_opens = None
        if metrics is not None:
            m = metrics.namespace("overload")
            self._m_state = m.gauge(
                "breaker_open", "1 while a worker's circuit is open",
                ["worker"])
            self._m_opens = m.counter(
                "breaker_opens_total", "Circuit-open transitions",
                ["worker"])

    def breaker(self, worker_id: int) -> CircuitBreaker:
        b = self._breakers.get(worker_id)
        if b is None:
            b = self._breakers[worker_id] = CircuitBreaker(
                self.cfg, self._clock)
        return b

    def state(self, worker_id: int) -> str:
        b = self._breakers.get(worker_id)
        return b.state if b else CLOSED

    def admitted(self, worker_ids: Iterable[int]) -> list[int]:
        """The subset a scheduler may route to right now (half-open
        probes included, one at a time per worker)."""
        return [w for w in worker_ids if self.breaker(w).allows()]

    def on_dispatch(self, worker_id: int) -> None:
        self.breaker(worker_id).on_dispatch()

    def record_success(self, worker_id: int,
                       latency_s: float | None = None,
                       cause: str | None = None) -> None:
        """``cause``: the journal ref of whatever proved the worker
        healthy (a canary_ok probe passes its own event) — plain
        request-plane successes leave it None."""
        b = self.breaker(worker_id)
        before = b.state
        b.record_success(latency_s)
        if before != CLOSED and b.state == CLOSED:
            log.info("worker %x circuit closed (probe succeeded)", worker_id)
            journal.emit(EventKind.BREAKER_TRANSITION, cause=cause,
                         worker_id=f"{worker_id:x}",
                         **{"from": before, "to": CLOSED})
            self._publish(worker_id)

    def record_failure(self, worker_id: int,
                       cause: str | None = None) -> None:
        """``cause``: the journal ref of the failure's origin when the
        caller knows it (a canary_fail probe passes its own event);
        with chaos armed, an open without an explicit cause names the
        most recent injection — the decision that opened the breaker is
        attributable either way."""
        b = self.breaker(worker_id)
        before = b.state
        b.record_failure()
        if b.state == OPEN and before != OPEN:
            log.warning("worker %x circuit OPEN after %d consecutive "
                        "failures; excluded for %.1fs", worker_id,
                        b.streak, self.cfg.breaker_cooldown_s)
            if cause is None:
                cause = journal.recent_ref(EventKind.CHAOS_INJECT)
            journal.emit(EventKind.BREAKER_TRANSITION, cause=cause,
                         worker_id=f"{worker_id:x}", streak=b.streak,
                         cooldown_s=self.cfg.breaker_cooldown_s,
                         **{"from": before, "to": OPEN})
            if self._m_opens is not None:
                self._m_opens.inc(worker=f"{worker_id:x}")
            self._publish(worker_id)

    def hold(self, worker_id: int, cause: str | None = None) -> None:
        """Canary-gated join: hold this worker's breaker — NO user
        traffic, not even half-open probes — until something records a
        success (the canary's direct-routed probe, which bypasses
        breaker filtering). ``cause`` is the journal ref that put it on
        probation (the worker_join event)."""
        b = self.breaker(worker_id)
        if b.probation:
            return
        before = b.state
        b.probation = True
        b.state = OPEN
        b.opened_t = self._clock()
        journal.emit(EventKind.BREAKER_TRANSITION, cause=cause,
                     worker_id=f"{worker_id:x}", reason="probation",
                     **{"from": before, "to": OPEN})
        self._publish(worker_id)

    def remove(self, worker_id: int) -> None:
        self._breakers.pop(worker_id, None)

    def _publish(self, worker_id: int) -> None:
        if self._m_state is not None:
            b = self._breakers[worker_id]
            self._m_state.set(1.0 if b.state == OPEN else 0.0,
                              worker=f"{worker_id:x}")
