"""Structured background-task tracking.

Capability parity with reference tracker.rs (lib/runtime/src/utils/tasks/
tracker.rs: TaskTracker + OnErrorPolicy / SchedulingPolicy / critical
handles): spawn supervised asyncio tasks with per-task error policies —
log-and-stop, retry with exponential backoff, or critical (failure
triggers runtime shutdown) — a concurrency-limiting scheduler, cancel-all
shutdown, and success/failure/retry counters.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import time
from typing import Any, Awaitable, Callable

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("tracker")


class OnError(enum.Enum):
    """Error policy (tracker.rs OnErrorPolicy)."""
    LOG = "log"           # record the failure, task ends
    RETRY = "retry"       # re-run with exponential backoff up to a limit
    CRITICAL = "critical"  # failure calls the tracker's on_critical hook


@dataclasses.dataclass
class TaskRecord:
    name: str
    policy: OnError
    started_at: float
    attempts: int = 0
    done: bool = False
    failed: bool = False
    cancelled: bool = False
    error: str | None = None
    result: Any = None


class TrackedHandle:
    """Await-able handle to a tracked task (tracker.rs TaskHandle)."""

    def __init__(self, record: TaskRecord, task: asyncio.Task):
        self.record = record
        self._task = task

    def __await__(self):
        return self._task.__await__()

    def cancel(self) -> None:
        self._task.cancel()

    @property
    def done(self) -> bool:
        return self._task.done()


class TaskTracker:
    def __init__(self, max_concurrency: int | None = None,
                 on_critical: Callable[[str, BaseException], None]
                 | None = None):
        self._sem = (asyncio.Semaphore(max_concurrency)
                     if max_concurrency else None)
        self._on_critical = on_critical
        self._tasks: set[asyncio.Task] = set()
        self.records: list[TaskRecord] = []
        self.succeeded = 0
        self.failed = 0
        self.retried = 0
        self._closed = False

    def spawn(self, name: str, fn: Callable[[], Awaitable],
              policy: OnError = OnError.LOG, max_retries: int = 3,
              backoff_s: float = 0.05) -> TrackedHandle:
        """Supervise ``fn`` (a zero-arg coroutine factory — retries need to
        re-create the coroutine)."""
        if self._closed:
            raise RuntimeError("tracker is shut down")
        record = TaskRecord(name=name, policy=policy,
                            started_at=time.monotonic())
        self.records.append(record)
        task = asyncio.create_task(
            self._run(record, fn, max_retries, backoff_s), name=name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return TrackedHandle(record, task)

    async def _run(self, record: TaskRecord, fn, max_retries: int,
                   backoff_s: float):
        while True:
            record.attempts += 1
            try:
                if self._sem is not None:
                    async with self._sem:
                        result = await fn()
                else:
                    result = await fn()
            except asyncio.CancelledError:
                record.cancelled = True
                record.done = True
                raise
            except Exception as exc:  # noqa: BLE001 — supervision point
                record.error = f"{type(exc).__name__}: {exc}"
                if (record.policy is OnError.RETRY
                        and record.attempts <= max_retries):
                    self.retried += 1
                    delay = backoff_s * (2 ** (record.attempts - 1))
                    log.warning("task %s failed (%s); retry %d/%d in %.2fs",
                                record.name, record.error, record.attempts,
                                max_retries, delay)
                    await asyncio.sleep(delay)
                    continue
                record.failed = True
                record.done = True
                self.failed += 1
                if record.policy is OnError.CRITICAL:
                    log.error("CRITICAL task %s failed: %s", record.name,
                              record.error)
                    if self._on_critical is not None:
                        self._on_critical(record.name, exc)
                else:
                    log.warning("task %s failed: %s", record.name,
                                record.error)
                raise
            else:
                record.done = True
                record.result = result
                self.succeeded += 1
                return result

    @property
    def active_count(self) -> int:
        return len(self._tasks)

    async def shutdown(self, timeout_s: float = 5.0) -> None:
        """Cancel everything still running and wait (tracker.rs
        cancel-all)."""
        self._closed = True
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.wait(list(self._tasks), timeout=timeout_s)
