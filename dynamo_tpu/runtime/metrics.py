"""Hierarchical metrics registry.

Capability parity with reference MetricsRegistry (lib/runtime/src/metrics.rs):
a tree of registries (runtime -> namespace -> component -> endpoint) whose
constituents auto-label every metric with its position in the hierarchy
(metrics.rs auto-labels; names in metrics/prometheus_names.rs). Backed by
prometheus_client; exposition text is served by the system status server.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Sequence

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Metric name prefix (reference: prometheus_names.rs uses "dynamo_*").
PREFIX = "dynamo_tpu"

HIER_LABELS = ("dynamo_namespace", "dynamo_component", "dynamo_endpoint")


class HistogramValue(NamedTuple):
    """Snapshot of a histogram child: observation count + sum."""

    count: int
    total: float


class MetricsRegistry:
    """A node in the metrics hierarchy. Children share the root collector
    registry; each level fills in one more hierarchy label."""

    def __init__(
        self,
        registry: CollectorRegistry | None = None,
        hierarchy: tuple[str, str, str] = ("", "", ""),
        _root: "MetricsRegistry | None" = None,
    ) -> None:
        self.registry = registry or CollectorRegistry()
        self._hierarchy = hierarchy
        self._root = _root or self
        if _root is None:
            self._metrics: dict[str, object] = {}
            self._lock = threading.Lock()

    def child(self, level: int, name: str) -> "MetricsRegistry":
        hier = list(self._hierarchy)
        hier[level] = name
        return MetricsRegistry(self.registry, tuple(hier), self._root)

    def namespace(self, name: str) -> "MetricsRegistry":
        return self.child(0, name)

    def component(self, name: str) -> "MetricsRegistry":
        return self.child(1, name)

    def endpoint(self, name: str) -> "MetricsRegistry":
        return self.child(2, name)

    # -- metric constructors -------------------------------------------------
    def _get_or_create(self, kind, name: str, desc: str,
                       extra_labels: Sequence[str], **kwargs):
        full = f"{PREFIX}_{name}"
        labelnames = tuple(HIER_LABELS) + tuple(extra_labels)
        root = self._root
        with root._lock:
            entry = root._metrics.get(full)
            if entry is None:
                metric = kind(full, desc, labelnames=labelnames,
                              registry=self.registry, **kwargs)
                root._metrics[full] = (metric, kind, labelnames)
                return metric
            metric, known_kind, known_labels = entry
            # Same name, different shape: without this check the first
            # registration silently wins and prometheus_client throws a
            # confusing labels() error at CALL time, far from the bug.
            if known_kind is not kind:
                raise ValueError(
                    f"metric {full!r} already registered as "
                    f"{known_kind.__name__}, cannot re-register as "
                    f"{kind.__name__}")
            if known_labels != labelnames:
                raise ValueError(
                    f"metric {full!r} already registered with labels "
                    f"{list(known_labels)}, cannot re-register with "
                    f"{list(labelnames)}")
        return metric

    def counter(self, name: str, desc: str, labels: Sequence[str] = ()):
        metric = self._get_or_create(Counter, name, desc, labels)
        return _Bound(metric, self._hierarchy, labels)

    def gauge(self, name: str, desc: str, labels: Sequence[str] = ()):
        metric = self._get_or_create(Gauge, name, desc, labels)
        return _Bound(metric, self._hierarchy, labels)

    def histogram(self, name: str, desc: str, labels: Sequence[str] = (),
                  buckets: Sequence[float] | None = None):
        kwargs = {"buckets": tuple(buckets)} if buckets else {}
        metric = self._get_or_create(Histogram, name, desc, labels, **kwargs)
        return _Bound(metric, self._hierarchy, labels)

    def expose(self) -> bytes:
        """Prometheus text exposition for /metrics."""
        return generate_latest(self.registry)


class _Bound:
    """A metric pre-bound to its hierarchy labels; extra labels at call time."""

    def __init__(self, metric, hierarchy: tuple[str, str, str],
                 extra_labels: Sequence[str]):
        self._metric = metric
        self._hier = hierarchy
        self._extra = tuple(extra_labels)

    def _resolve(self, **labels):
        vals = dict(zip(HIER_LABELS, self._hier))
        for k in self._extra:
            vals[k] = labels.get(k, "")
        return self._metric.labels(**vals)

    def inc(self, amount: float = 1.0, **labels):
        self._resolve(**labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels):
        self._resolve(**labels).dec(amount)

    def set(self, value: float, **labels):
        self._resolve(**labels).set(value)

    def observe(self, value: float, **labels):
        self._resolve(**labels).observe(value)

    def ensure(self, **labels) -> None:
        """Instantiate the labeled child so the series shows up in
        exposition before its first update (dashboards see zeros, not
        absent series)."""
        self._resolve(**labels)

    def collect(self) -> dict[tuple[str, ...], float]:
        """Snapshot every instantiated child of this metric at THIS
        hierarchy position: extra-label value tuple -> current value.
        Counters/gauges only (histograms: use get() per label set).
        Lets callers enumerate label combinations they didn't create —
        e.g. summing shed_total across every (reason, priority) to
        assert zero silent drops."""
        out: dict[tuple[str, ...], float] = {}
        children = getattr(self._metric, "_metrics", {})
        n_hier = len(self._hier)
        for labelvalues, child in list(children.items()):
            if tuple(labelvalues[:n_hier]) != self._hier:
                continue
            if not hasattr(child, "_value"):
                raise TypeError(
                    f"collect() unsupported for "
                    f"{type(self._metric).__name__}")
            out[tuple(labelvalues[n_hier:])] = child._value.get()
        return out

    def get(self, **labels):
        """Current value: float for counters/gauges, HistogramValue
        (count, total) for histograms. Raises TypeError for metric types
        with neither, instead of poking missing internals."""
        child = self._resolve(**labels)
        # prometheus_client internals: _value for counter/gauge.
        if hasattr(child, "_value"):
            return child._value.get()
        if hasattr(child, "_sum"):  # histogram
            # _buckets holds per-bucket (non-cumulative) counts; the
            # observation count is their sum.
            return HistogramValue(
                count=int(sum(b.get() for b in child._buckets)),
                total=child._sum.get())
        raise TypeError(
            f"get() unsupported for {type(self._metric).__name__}")
