"""System status server: /health, /live, /metrics.

Capability parity with reference spawn_system_status_server
(lib/runtime/src/system_status_server.rs:85-121) and SystemHealth
(lib.rs:90-120): per-process HTTP server exposing liveness, per-endpoint health,
and Prometheus metrics, gated by config (DTPU_SYSTEM_ENABLED/PORT ~
DYN_SYSTEM_*, config.rs:85-123).
"""

from __future__ import annotations

import json

from aiohttp import web

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("health")


class SystemStatusServer:
    def __init__(self, runtime, host: str = "0.0.0.0", port: int = 0):
        self._runtime = runtime
        self.host, self.port = host, port
        self._endpoint_health: dict[str, bool] = {}
        self._runner: web.AppRunner | None = None

    def set_endpoint_health(self, endpoint_path: str, healthy: bool) -> None:
        self._endpoint_health[endpoint_path] = healthy

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("system status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, _request: web.Request) -> web.Response:
        healthy = all(self._endpoint_health.values()) if self._endpoint_health else True
        body = {"status": "healthy" if healthy else "unhealthy",
                "endpoints": self._endpoint_health}
        return web.Response(text=json.dumps(body), status=200 if healthy else 503,
                            content_type="application/json")

    async def _live(self, _request: web.Request) -> web.Response:
        return web.Response(text=json.dumps({"status": "live"}),
                            content_type="application/json")

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(body=self._runtime.metrics.expose(),
                            content_type="text/plain")
