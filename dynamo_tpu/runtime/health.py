"""System status server: /health, /live, /metrics, /debug/*.

Capability parity with reference spawn_system_status_server
(lib/runtime/src/system_status_server.rs:85-121) and SystemHealth
(lib.rs:90-120): per-process HTTP server exposing liveness, per-endpoint health,
and Prometheus metrics, gated by config (DTPU_SYSTEM_ENABLED/PORT ~
DYN_SYSTEM_*, config.rs:85-123). On top of the reference's surface it also
serves the tracing/SLO/accounting/flight debug API:

- ``GET /debug/traces/recent``            — newest-first trace index
- ``GET /debug/traces?trace_id=&format=`` — one trace (chrome|otlp|spans)
- ``POST /debug/profile``                 — on-demand jax.profiler capture
  (``{"duration_ms": 1000, "out_dir": "/tmp/prof"}``), degrading to a
  span-recorder dump when JAX profiling is unavailable.
- ``GET /debug/slo``                      — SLO targets, windowed SLIs,
  burn rates, alert states, pressure (runtime/slo.py)
- ``GET /debug/requests?limit=``          — newest-first per-request
  accounting records (llm/recorder.py RequestLedger)
- ``GET /debug/flight``                   — flight-recorder ring +
  meta; ``POST /debug/flight`` captures a diagnostic bundle to disk
  (``{"out_dir": ...}`` optional; runtime/flight.py)
- ``GET /debug/kv``                       — this process's KV/capacity
  view (docs/OBSERVABILITY.md "KV & capacity"): on a worker, the
  engine's allocator/tier/plane stats + inventory digest; on a
  frontend, the KV router's fleet view + decision telemetry. The
  provider is per-app (``app[KV_PROVIDER]``), NOT process-global, so
  in-process multi-worker tests keep distinct panes.
- ``GET /debug/perf``                     — the engine perf plane
  (docs/OBSERVABILITY.md "Engine perf plane"): per-program compile
  stats + unexpected-recompile detector, roofline-attributed window
  timing, HBM gauges, memory breakdown. Per-app provider like
  ``/debug/kv`` (``TPUEngine.perf_status``); without one the
  process-global compile observatory still answers.
"""

from __future__ import annotations

import asyncio
import json
import tempfile

from aiohttp import web

from dynamo_tpu.runtime import flight, slo, tracing
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("health")


#: App key under which a process registers its /debug/kv provider — a
#: zero-arg callable returning the JSON-able KV status dict (e.g.
#: TPUEngine.kv_status, MockerEngine.kv_status, KvPushRouter.kv_status).
try:
    KV_PROVIDER = web.AppKey("dtpu_kv_provider", object)
except AttributeError:  # older aiohttp: plain string keys
    KV_PROVIDER = "dtpu_kv_provider"

#: App key for the /debug/perf provider (e.g. TPUEngine.perf_status).
try:
    PERF_PROVIDER = web.AppKey("dtpu_perf_provider", object)
except AttributeError:  # older aiohttp: plain string keys
    PERF_PROVIDER = "dtpu_perf_provider"

#: App key for the /debug/timeline provider. A frontend registers its
#: TimelineCollector (llm/timeline.py) so the route serves the MERGED
#: fleet timeline; a worker serves its own process journal.
try:
    TIMELINE_PROVIDER = web.AppKey("dtpu_timeline_provider", object)
except AttributeError:  # older aiohttp: plain string keys
    TIMELINE_PROVIDER = "dtpu_timeline_provider"


def add_debug_routes(app: web.Application,
                     kv_provider=None, perf_provider=None,
                     timeline_provider=None) -> None:
    """Attach the observability debug routes (shared with the OpenAI
    frontend so in-process pipelines get them without a status server)."""
    app.router.add_get("/debug/traces", _debug_traces)
    app.router.add_get("/debug/traces/recent", _debug_traces_recent)
    app.router.add_post("/debug/profile", _debug_profile)
    app.router.add_get("/debug/slo", _debug_slo)
    app.router.add_get("/debug/requests", _debug_requests)
    app.router.add_get("/debug/flight", _debug_flight)
    app.router.add_post("/debug/flight", _debug_flight_capture)
    app.router.add_get("/debug/kv", _debug_kv)
    app.router.add_get("/debug/perf", _debug_perf)
    app.router.add_get("/debug/timeline", _debug_timeline)
    if kv_provider is not None:
        app[KV_PROVIDER] = kv_provider
    if perf_provider is not None:
        app[PERF_PROVIDER] = perf_provider
    if timeline_provider is not None:
        app[TIMELINE_PROVIDER] = timeline_provider


async def _debug_timeline(request: web.Request) -> web.Response:
    """The decision plane (docs/OBSERVABILITY.md "Decision plane"): on
    a frontend, the causally ordered merged fleet timeline; on a worker
    (or any process without a collector), this process's own journal."""
    provider = request.app.get(TIMELINE_PROVIDER)
    try:
        limit = int(request.query.get("limit", "512"))
    except ValueError:
        return web.json_response({"error": "limit must be an integer"},
                                 status=400)
    if provider is None:
        from dynamo_tpu.runtime import journal
        body = {"role": "process", **journal.get_journal().snapshot(limit)}
        return web.json_response(body)
    try:
        body = provider(limit)
    except Exception as exc:  # noqa: BLE001 — a pane, not a crash vector
        log.exception("timeline provider failed")
        return web.json_response(
            {"error": f"timeline provider failed: {exc}"}, status=500)
    return web.json_response(body)


async def _debug_perf(request: web.Request) -> web.Response:
    provider = request.app.get(PERF_PROVIDER)
    if provider is None:
        # The compile observatory is process-global: a process without
        # an engine (proxy frontend, bare status server) still reports
        # its own jit programs — just no HBM/window attribution.
        from dynamo_tpu.engine.perf import process_perf_status
        provider = process_perf_status
    try:
        body = provider()
    except Exception as exc:  # noqa: BLE001 — a pane, not a crash vector
        log.exception("perf status provider failed")
        return web.json_response({"error": f"perf provider failed: {exc}"},
                                 status=500)
    return web.json_response(body)


async def _debug_kv(request: web.Request) -> web.Response:
    provider = request.app.get(KV_PROVIDER)
    if provider is None:
        return web.json_response(
            {"error": "no KV status provider on this process (a worker "
             "registers its engine, a KV-mode frontend its router)"},
            status=404)
    try:
        body = provider()
    except Exception as exc:  # noqa: BLE001 — a pane, not a crash vector
        log.exception("kv status provider failed")
        return web.json_response({"error": f"kv provider failed: {exc}"},
                                 status=500)
    return web.json_response(body)


async def _debug_slo(_request: web.Request) -> web.Response:
    return web.json_response(slo.get_plane().snapshot())


async def _debug_requests(request: web.Request) -> web.Response:
    from dynamo_tpu.llm.recorder import get_ledger
    limit = int(request.query.get("limit", "100"))
    return web.json_response(get_ledger().snapshot(limit))


async def _debug_flight(_request: web.Request) -> web.Response:
    rec = flight.get_recorder()
    return web.json_response({"meta": rec.meta(), "windows": rec.dump(),
                              "triggers_total": flight.triggers_total})


async def _debug_flight_capture(request: web.Request) -> web.Response:
    try:
        body = await request.json()
    except (json.JSONDecodeError, ValueError):
        body = {}
    out_dir = body.get("out_dir")
    reason = str(body.get("reason", "manual"))
    # The bundle serializes the whole ring + span recorder: off the loop.
    path = await asyncio.to_thread(flight.capture_bundle, reason, out_dir)
    return web.json_response({"bundle": path, "reason": reason})


async def _debug_traces_recent(request: web.Request) -> web.Response:
    limit = int(request.query.get("limit", "50"))
    return web.json_response(tracing.traces_index(limit=limit))


async def _debug_traces(request: web.Request) -> web.Response:
    trace_id = request.query.get("trace_id")
    if not trace_id:
        return await _debug_traces_recent(request)
    fmt = request.query.get("format", "chrome")
    try:
        payload = tracing.trace_payload(trace_id, fmt)
    except ValueError as exc:
        return web.json_response({"error": str(exc)}, status=400)
    if payload is None:
        return web.json_response(
            {"error": f"trace {trace_id!r} not found (evicted or never "
             "recorded; recorder enabled="
             f"{tracing.get_recorder().enabled})"}, status=404)
    return web.json_response(payload)


async def _debug_profile(request: web.Request) -> web.Response:
    try:
        body = await request.json()
    except (json.JSONDecodeError, ValueError):
        body = {}
    duration_ms = int(body.get("duration_ms", 1000))
    out_dir = body.get("out_dir") or tempfile.mkdtemp(prefix="dtpu-profile-")
    try:
        result = await tracing.capture_profile(duration_ms, out_dir)
    except RuntimeError as exc:  # capture already running
        return web.json_response({"error": str(exc)}, status=409)
    log.info("profile captured: %s", result)
    return web.json_response(result)


class SystemStatusServer:
    def __init__(self, runtime, host: str = "0.0.0.0", port: int = 0,
                 role_manager=None, kv_provider=None, perf_provider=None,
                 scale_agent=None):
        self._runtime = runtime
        self.host, self.port = host, port
        self._endpoint_health: dict[str, bool] = {}
        self._runner: web.AppRunner | None = None
        # llm/reconfig.RoleManager: enables the SetRole control verb on
        # this worker's status path (GET/POST /control/role).
        self.role_manager = role_manager
        # llm/standby.ScaleAgent: enables the scale control verb
        # (GET/POST /control/scale — standby state, operator
        # promote/retire without going through the planner).
        self.scale_agent = scale_agent
        # /debug/kv provider for THIS worker (engine.kv_status).
        self.kv_provider = kv_provider
        # /debug/perf provider (engine.perf_status).
        self.perf_provider = perf_provider

    def set_endpoint_health(self, endpoint_path: str, healthy: bool) -> None:
        self._endpoint_health[endpoint_path] = healthy

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/control/role", self._role_status)
        app.router.add_post("/control/role", self._role_set)
        app.router.add_get("/control/scale", self._scale_status)
        app.router.add_post("/control/scale", self._scale_apply)
        add_debug_routes(app, kv_provider=self.kv_provider,
                         perf_provider=self.perf_provider)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("system status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, _request: web.Request) -> web.Response:
        healthy = all(self._endpoint_health.values()) if self._endpoint_health else True
        body = {"status": "healthy" if healthy else "unhealthy",
                "endpoints": self._endpoint_health}
        return web.Response(text=json.dumps(body), status=200 if healthy else 503,
                            content_type="application/json")

    async def _live(self, _request: web.Request) -> web.Response:
        return web.Response(text=json.dumps({"status": "live"}),
                            content_type="application/json")

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(body=self._runtime.metrics.expose(),
                            content_type="text/plain")

    # -- Scale control verb (llm/standby.py; docs/RESILIENCE.md) --------------
    async def _scale_status(self, _request: web.Request) -> web.Response:
        if self.scale_agent is None:
            return web.json_response(
                {"error": "no scale agent on this worker"}, status=404)
        return web.json_response(self.scale_agent.standby_status())

    async def _scale_apply(self, request: web.Request) -> web.Response:
        """POST /control/scale {"action": "promote"|"retire", "epoch": N,
        "role"?} — the operator-facing scale verb (same shape as the
        coordinator directive, fenced identically; a replayed curl
        cannot re-apply). Fencing rejections answer 409 typed."""
        from dynamo_tpu.runtime.errors import RoleTransitionError
        if self.scale_agent is None:
            return web.json_response(
                {"error": "no scale agent on this worker"}, status=404)
        try:
            body = await request.json()
        except (json.JSONDecodeError, ValueError):
            return web.json_response({"error": "invalid JSON body"},
                                     status=400)
        action = body.get("action")
        epoch = body.get("epoch")
        if action not in ("promote", "retire") or not isinstance(epoch, int):
            return web.json_response(
                {"error": "body must carry action:promote|retire and "
                 "epoch:int (above the applied epoch in "
                 "GET /control/scale)"}, status=400)
        directive = {**body, "issued_by": str(body.get("issued_by",
                                                       "http"))}
        try:
            if action == "promote":
                await self.scale_agent._promote(directive)
            else:
                await self.scale_agent._retire(directive)
        except RoleTransitionError as exc:
            return web.json_response(
                {"error": str(exc), "type": "role_transition"}, status=409)
        return web.json_response(self.scale_agent.standby_status())

    # -- SetRole control verb (llm/reconfig.py; docs/RESILIENCE.md) -----------
    async def _role_status(self, _request: web.Request) -> web.Response:
        if self.role_manager is None:
            return web.json_response(
                {"error": "no role manager on this worker"}, status=404)
        return web.json_response(self.role_manager.status())

    async def _role_set(self, request: web.Request) -> web.Response:
        """POST /control/role {"role": "prefill", "epoch": 7} — the
        operator-facing SetRole verb. Fencing rejections (stale epoch,
        flip in flight) answer 409 with the typed error; the epoch is
        REQUIRED so a replayed curl can't accidentally re-flip."""
        from dynamo_tpu.runtime.errors import RoleTransitionError
        if self.role_manager is None:
            return web.json_response(
                {"error": "no role manager on this worker"}, status=404)
        try:
            body = await request.json()
        except (json.JSONDecodeError, ValueError):
            return web.json_response({"error": "invalid JSON body"},
                                     status=400)
        role = body.get("role")
        epoch = body.get("epoch")
        if not isinstance(role, str) or not isinstance(epoch, int):
            return web.json_response(
                {"error": "body must carry role:str and epoch:int "
                 "(epoch must be above the applied epoch in "
                 "GET /control/role)"}, status=400)
        try:
            outcome = await self.role_manager.set_role(
                role, epoch, issued_by=str(body.get("issued_by", "http")),
                drain_s=body.get("drain_s"))
        except RoleTransitionError as exc:
            return web.json_response(
                {"error": str(exc), "type": "role_transition"}, status=409)
        return web.json_response(outcome)
