"""Length-prefixed msgpack framing over asyncio streams.

This is the wire codec for both the control plane (coordinator) and the request/
response plane. Capability parity with the reference TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs): a frame is a 4-byte
big-endian length followed by a msgpack map; request/response payloads embed a
separate ``header``/``data`` split inside the map, preserving the two-part shape
without a bespoke binary layout.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

from dynamo_tpu.runtime import chaos

MAX_FRAME = 256 * 1024 * 1024  # 256 MiB hard cap
_LEN = struct.Struct(">I")


def encode_frame(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(body)}")
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader,
                     chaos_site: str | None = None) -> Any:
    """Read one frame; raises asyncio.IncompleteReadError on clean EOF.

    ``chaos_site`` labels this choke point for fault injection
    (runtime/chaos.py); with no plan armed the guard is a single bool
    check."""
    if chaos.ACTIVE:
        await chaos.on_frame_read(chaos_site)
    header = await reader.readexactly(4)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


async def write_frame(writer: asyncio.StreamWriter, obj: Any,
                      chaos_site: str | None = None) -> None:
    data = encode_frame(obj)
    if chaos.ACTIVE:
        data = await chaos.on_frame_write(writer, data, chaos_site)
        if data is None:  # frame dropped by the plan
            return
    writer.write(data)
    await writer.drain()
