"""Request context: identity, cancellation, tracing.

Capability parity with reference AsyncEngineContext (lib/runtime/src/engine.rs:124)
and pipeline Context (lib/runtime/src/pipeline/context.rs): every request carries a
stable id, a two-level cancellation signal (stop = graceful stop issuing final
response; kill = hard abort), and trace context for distributed tracing.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any

from dynamo_tpu.runtime.logging import (generate_span_id, generate_trace_id,
                                        make_traceparent, parse_traceparent)


class Context:
    def __init__(self, request_id: str | None = None,
                 trace_id: str | None = None, parent_span_id: str | None = None):
        self.id: str = request_id or uuid.uuid4().hex
        self.trace_id: str = trace_id or generate_trace_id()
        self.span_id: str = generate_span_id()
        self.parent_span_id = parent_span_id
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        # Arbitrary cross-operator annotations (reference: context values).
        self.values: dict[str, Any] = {}

    # -- cancellation (engine.rs:124 stop_generating/kill) --------------------
    def stop_generating(self) -> None:
        """Ask the engine to finish up: emit its final usage/finish response
        then end the stream."""
        self._stopped.set()

    def kill(self) -> None:
        """Hard-abort: no further responses should be produced."""
        self._stopped.set()
        self._killed.set()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def wait_stopped(self) -> None:
        # Cancellation watcher by design: callers hold this as a task
        # and cancel it when the stream ends.
        # dtpu: ignore[unbounded-wait] -- see above
        await self._stopped.wait()

    def child(self) -> "Context":
        """New span in the same trace, sharing cancellation."""
        ctx = Context(self.id, self.trace_id, self.span_id)
        ctx._stopped = self._stopped
        ctx._killed = self._killed
        ctx.values = self.values
        return ctx

    def to_wire(self) -> dict:
        # The W3C traceparent rides every inter-component frame alongside
        # the explicit ids, so a frontend trace id shows up in worker
        # spans (distributed tracing, not per-process timing).
        return {"id": self.id, "trace_id": self.trace_id,
                "span_id": self.span_id,
                "traceparent": make_traceparent(self.trace_id, self.span_id)}

    @classmethod
    def from_wire(cls, data: dict | None) -> "Context":
        data = data or {}
        trace_id, parent_id = data.get("trace_id"), data.get("span_id")
        if trace_id is None and data.get("traceparent"):
            # Frames from peers that only speak W3C: parse the header.
            parsed = parse_traceparent(data["traceparent"])
            if parsed:
                trace_id, parent_id = parsed["trace_id"], parsed["parent_id"]
        return cls(data.get("id"), trace_id, parent_id)
