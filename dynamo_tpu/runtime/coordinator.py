"""Built-in control-plane coordinator: KV + leases + watch + pub/sub + queues.

The reference runs two external servers — etcd for discovery/lease/config
(lib/runtime/src/transports/etcd.rs:46-414) and NATS(+JetStream) for pub/sub and
work queues (transports/nats.rs:58-600). This module provides one self-contained
asyncio TCP server with the union of the semantics the reference actually uses:

- etcd-shaped:  kv_put / kv_create (atomic create, etcd.rs kv_create txn) /
  kv_get / kv_get_prefix / kv_delete, lease grant/keepalive/revoke with TTL
  expiry cascading key deletes, and prefix watches streaming put/delete events
  (etcd.rs kv_get_and_watch_prefix -> PrefixWatcher).
- NATS-shaped:  publish/subscribe on '.'-separated subjects with prefix
  wildcard, and persistent work queues with blocking pop
  (NatsQueue::{enqueue_task,dequeue_task}, nats.rs:433-600) plus an object
  store (object_put/object_get, nats.rs:174 — ships tokenizer artifacts).

Liveness: instance registration keys are attached to a lease; process death =>
keepalives stop => lease expires => watchers see delete events and deregister
the worker (SURVEY.md §5.3). A single coordinator is the deployment-unit
equivalent of the reference's etcd+NATS pair; it is NOT on the data path (KV
blocks and token streams never transit it).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from collections import deque
from typing import Any

from dynamo_tpu.runtime.frame import read_frame, write_frame
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("coordinator")


class _Lease:
    __slots__ = ("id", "ttl", "expires_at", "keys")

    def __init__(self, lease_id: int, ttl: float):
        self.id = lease_id
        self.ttl = ttl
        self.expires_at = time.monotonic() + ttl
        self.keys: set[str] = set()

    def refresh(self) -> None:
        self.expires_at = time.monotonic() + self.ttl


OUTBOX_LIMIT = 4096  # frames buffered per connection before we drop the peer


class _Conn:
    """Per-client connection state. Watch/sub ids are allocated by the client
    (unique per connection) so the client can register its event queue before
    the first event can possibly arrive.

    Sends go through a bounded per-connection outbox drained by a writer task,
    so one stalled client socket can never block KV mutations, lease expiry, or
    fan-out to other clients; a client that falls OUTBOX_LIMIT frames behind is
    disconnected (slow-consumer policy, as NATS does)."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.watches: dict[int, str] = {}  # wid -> prefix
        self.subs: dict[int, str] = {}  # sid -> pattern
        self.closed = False
        # Control-plane writer queue: producers are coordinator-local
        # event fan-out (watch/pubsub deltas, no user payload
        # amplification); bounding would make kv_put on one slow peer
        # block every other peer's watch delivery.
        # dtpu: ignore[unbounded-queue] -- see above
        self._outbox: asyncio.Queue = asyncio.Queue()
        self._writer_task = asyncio.create_task(self._write_loop())

    async def _write_loop(self) -> None:
        try:
            while True:
                obj = await self._outbox.get()
                await write_frame(self.writer, obj, chaos_site="coord")
        except asyncio.CancelledError:
            raise  # close() cancelled us; finally still runs the cleanup
        except (ConnectionError, OSError):
            pass
        finally:
            self.closed = True
            self.writer.close()

    async def send(self, obj: Any) -> None:
        if self.closed:
            return
        if self._outbox.qsize() >= OUTBOX_LIMIT:
            log.warning("dropping slow coordinator client (outbox full)")
            self.close()
            return
        self._outbox.put_nowait(obj)

    def close(self) -> None:
        self.closed = True
        self._writer_task.cancel()


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style matching: tokens split on '.', '*' matches one token,
    trailing '>' matches the rest."""
    pt, st = pattern.split("."), subject.split(".")
    for i, p in enumerate(pt):
        if p == ">":
            return True
        if i >= len(st):
            return False
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


class Coordinator:
    """The control-plane server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None
        self._ids = itertools.count(1)
        self._revision = 0
        # key -> (value, lease_id|None, revision)
        self._kv: dict[str, tuple[Any, int | None, int]] = {}
        self._leases: dict[int, _Lease] = {}
        self._conns: set[_Conn] = set()
        self._queues: dict[str, deque] = {}
        self._queue_waiters: dict[str, deque[asyncio.Future]] = {}
        self._objects: dict[str, bytes] = {}
        self._expiry_task: asyncio.Task | None = None

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.create_task(self._expiry_loop())
        log.info("coordinator listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._server:
            self._server.close()
            # Close live client connections so wait_closed() (which waits for
            # all handlers on Python 3.12+) can complete.
            for conn in list(self._conns):
                conn.close()
            await self._server.wait_closed()

    @property
    def url(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    # -- lease expiry ---------------------------------------------------------
    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            expired = [l for l in self._leases.values() if l.expires_at < now]
            for lease in expired:
                log.info("lease %d expired; deleting %d keys", lease.id, len(lease.keys))
                await self._revoke(lease)

    async def _revoke(self, lease: _Lease) -> None:
        self._leases.pop(lease.id, None)
        for key in list(lease.keys):
            await self._delete_key(key)

    async def _delete_key(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        _, lease_id, _ = entry
        if lease_id is not None and lease_id in self._leases:
            self._leases[lease_id].keys.discard(key)
        await self._notify_watchers("delete", key, None)
        return True

    async def _notify_watchers(self, ev: str, key: str, value: Any) -> None:
        for conn in list(self._conns):
            for wid, prefix in list(conn.watches.items()):
                if key.startswith(prefix):
                    await conn.send({"w": wid, "ev": ev, "k": key, "v": value})

    # -- connection handling --------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer)
        self._conns.add(conn)
        pending: set[asyncio.Task] = set()
        try:
            while True:
                msg = await read_frame(reader, chaos_site="coord")
                if msg.get("m") == "queue_pop":
                    # The only op that can block (timed wait for an item):
                    # run it off the read loop, holding a strong reference so
                    # it isn't garbage-collected mid-flight. Everything else
                    # dispatches inline, preserving per-connection ordering
                    # (e.g. two kv_puts, or a put/delete pair).
                    task = asyncio.ensure_future(self._dispatch(conn, msg))
                    pending.add(task)
                    task.add_done_callback(pending.discard)
                else:
                    await self._dispatch(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            for task in pending:
                task.cancel()
            conn.close()
            self._conns.discard(conn)

    async def _dispatch(self, conn: _Conn, msg: dict) -> None:
        rid = msg.get("i")
        try:
            result = await self._call(conn, msg)
            await conn.send({"i": rid, "ok": True, "r": result})
        except Exception as exc:  # noqa: BLE001 — report to client
            await conn.send({"i": rid, "ok": False, "e": f"{type(exc).__name__}: {exc}"})

    async def _call(self, conn: _Conn, msg: dict) -> Any:
        m = msg["m"]
        if m == "lease_grant":
            lease = _Lease(next(self._ids), float(msg["ttl"]))
            self._leases[lease.id] = lease
            return lease.id
        if m == "lease_keepalive":
            lease = self._leases.get(msg["lease"])
            if lease is None:
                raise KeyError(f"lease {msg['lease']} not found")
            lease.refresh()
            return True
        if m == "lease_revoke":
            lease = self._leases.get(msg["lease"])
            if lease is not None:
                await self._revoke(lease)
            return True
        if m == "kv_put":
            return await self._kv_put(msg["k"], msg["v"], msg.get("lease"))
        if m == "kv_create":
            if msg["k"] in self._kv:
                return None  # already exists (etcd txn failure)
            return await self._kv_put(msg["k"], msg["v"], msg.get("lease"))
        if m == "kv_get":
            entry = self._kv.get(msg["k"])
            return None if entry is None else {"v": entry[0], "rev": entry[2]}
        if m == "kv_get_prefix":
            prefix = msg["k"]
            return [{"k": k, "v": v, "rev": rev}
                    for k, (v, _, rev) in sorted(self._kv.items())
                    if k.startswith(prefix)]
        if m == "kv_delete":
            return await self._delete_key(msg["k"])
        if m == "kv_delete_prefix":
            keys = [k for k in self._kv if k.startswith(msg["k"])]
            for k in keys:
                await self._delete_key(k)
            return len(keys)
        if m == "watch":
            wid = msg["wid"]  # client-allocated
            conn.watches[wid] = msg["k"]
            snapshot = [{"k": k, "v": v, "rev": rev}
                        for k, (v, _, rev) in sorted(self._kv.items())
                        if k.startswith(msg["k"])]
            return {"watch_id": wid, "snapshot": snapshot}
        if m == "unwatch":
            conn.watches.pop(msg["watch_id"], None)
            return True
        if m == "publish":
            subject = msg["subject"]
            for sub_conn in list(self._conns):
                for sid, pattern in list(sub_conn.subs.items()):
                    if subject_matches(pattern, subject):
                        await sub_conn.send({"s": sid, "subject": subject,
                                             "payload": msg["payload"]})
            return True
        if m == "subscribe":
            sid = msg["sid"]  # client-allocated
            conn.subs[sid] = msg["subject"]
            return sid
        if m == "unsubscribe":
            conn.subs.pop(msg["sub"], None)
            return True
        if m == "queue_push":
            name = msg["queue"]
            waiters = self._queue_waiters.get(name)
            while waiters:
                fut = waiters.popleft()
                if not fut.done():
                    fut.set_result(msg["item"])
                    return True
            self._queues.setdefault(name, deque()).append(msg["item"])
            return True
        if m == "queue_pop":
            name = msg["queue"]
            q = self._queues.get(name)
            if q:
                return {"item": q.popleft()}
            timeout = msg.get("timeout", 0.0)
            if timeout <= 0:
                return None
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._queue_waiters.setdefault(name, deque()).append(fut)
            try:
                return {"item": await asyncio.wait_for(fut, timeout)}
            except asyncio.TimeoutError:
                return None
        if m == "queue_len":
            return len(self._queues.get(msg["queue"], ()))
        if m == "object_put":
            self._objects[msg["k"]] = msg["v"]
            return True
        if m == "object_get":
            return self._objects.get(msg["k"])
        raise ValueError(f"unknown method {m!r}")

    async def _kv_put(self, key: str, value: Any, lease_id: int | None) -> int:
        prev = self._kv.get(key)
        if prev is not None and prev[1] is not None and prev[1] != lease_id:
            # Re-owned key: detach from the previous lease so its expiry
            # doesn't delete the new owner's live key.
            old = self._leases.get(prev[1])
            if old is not None:
                old.keys.discard(key)
        if lease_id is not None:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise KeyError(f"lease {lease_id} not found")
            lease.keys.add(key)
        self._revision += 1
        self._kv[key] = (value, lease_id, self._revision)
        await self._notify_watchers("put", key, value)
        return self._revision


async def run_coordinator(host: str = "0.0.0.0", port: int = 4222) -> None:
    coord = Coordinator(host, port)
    await coord.start()
    try:
        # dtpu: ignore[unbounded-wait] -- serve-forever until killed
        await asyncio.Event().wait()
    finally:
        await coord.stop()


def main() -> None:  # python -m dynamo_tpu.runtime.coordinator
    import argparse

    parser = argparse.ArgumentParser(description="dynamo-tpu control-plane coordinator")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=4222)
    args = parser.parse_args()
    asyncio.run(run_coordinator(args.host, args.port))


if __name__ == "__main__":
    main()
