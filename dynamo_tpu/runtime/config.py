"""Layered runtime configuration.

Capability parity with the reference's figment-based config
(lib/runtime/src/config.rs:66-214): defaults <- optional TOML file <- environment
variables. Env prefix is ``DTPU_`` (reference uses ``DYN_RUNTIME_``/``DYN_SYSTEM_``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

from dynamo_tpu.runtime.overload import OverloadConfig
from dynamo_tpu.runtime.slo import SloConfig

try:  # tomllib is stdlib from 3.11; fall back to tomli, else TOML-less.
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - depends on interpreter
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # type: ignore[assignment]

ENV_PREFIX = "DTPU_"


def _env(name: str, default: str | None = None) -> str | None:
    return os.environ.get(ENV_PREFIX + name, default)


def _env_bool(name: str, default: bool) -> bool:
    raw = _env(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    raw = _env(name)
    return default if raw is None else int(raw)


def _env_float(name: str, default: float) -> float:
    raw = _env(name)
    return default if raw is None else float(raw)


def _apply_scalar_env(prefix: str, obj: Any) -> None:
    """Generic DTPU_<PREFIX>_<FIELD> override for all-scalar config
    dataclasses (OverloadConfig, SloConfig): the mapping is mechanical
    because every field is a plain bool/int/float/str."""
    for field in dataclasses.fields(type(obj)):
        raw = _env(f"{prefix}_" + field.name.upper())
        if raw is None:
            continue
        current = getattr(obj, field.name)
        if isinstance(current, bool):
            value: Any = raw.strip().lower() in ("1", "true", "yes", "on")
        elif isinstance(current, int):
            value = int(raw)
        elif isinstance(current, float):
            value = float(raw)
        else:
            value = raw
        setattr(obj, field.name, value)


@dataclasses.dataclass
class RuntimeConfig:
    """Node-level runtime settings.

    Mirrors reference RuntimeConfig (lib/runtime/src/config.rs:66) plus the
    DYN_SYSTEM_* health-server knobs (config.rs:85-123), collapsed into one
    dataclass because we have a single process model.
    """

    # Control plane (coordinator = etcd+NATS equivalent).
    coordinator_url: str = "tcp://127.0.0.1:4222"
    # Static mode: no discovery; endpoints are addressed directly
    # (reference: DistributedRuntime::from_settings_without_discovery,
    # lib/runtime/src/distributed.rs:178).
    static_mode: bool = False

    # Namespace default for this process.
    namespace: str = "dynamo"

    # Lease TTL for liveness (reference etcd lease, transports/etcd/lease.rs).
    lease_ttl_s: float = 10.0

    # Request-plane bind host for worker endpoints (0 => ephemeral port).
    bind_host: str = "127.0.0.1"
    advertise_host: str | None = None

    # System status server (reference system_status_server.rs:85-121).
    system_enabled: bool = False
    system_port: int = 0  # 0 => ephemeral

    # Async runtime sizing (reference worker/runtime threads; here: thread pools).
    num_worker_threads: int = 4

    # Graceful-shutdown drain timeout.
    shutdown_timeout_s: float = 10.0

    # How long a deregistered instance's in-flight streams may keep
    # draining before the request-plane connection is force-closed
    # (runtime/client.py retire-on-delete path).
    retire_drain_s: float = 30.0

    # Per-stream inter-frame deadline on the request plane: a stream
    # with no frames for this long fails typed (StreamIncompleteError
    # -> migration) instead of hanging on a zombie connection. 0
    # disables.
    stream_idle_timeout_s: float = 300.0

    # Overload defense (runtime/overload.py): adaptive admission,
    # deadline-aware shedding, per-worker circuit breakers, brownout.
    # TOML: an [overload] table; env: DTPU_OVERLOAD_<FIELD>.
    overload: OverloadConfig = dataclasses.field(
        default_factory=OverloadConfig)

    # SLO plane (runtime/slo.py): declarative targets, sliding-window
    # SLIs, multi-window burn-rate alerting, per-request accounting.
    # TOML: an [slo] table; env: DTPU_SLO_<FIELD>.
    slo: SloConfig = dataclasses.field(default_factory=SloConfig)

    @classmethod
    def from_settings(cls, path: str | None = None) -> "RuntimeConfig":
        """defaults <- TOML (DTPU_CONFIG_PATH or ``path``) <- DTPU_* env."""
        cfg = cls()
        toml_path = path or _env("CONFIG_PATH")
        if toml_path and os.path.exists(toml_path):
            if tomllib is None:
                raise RuntimeError(
                    f"config file {toml_path!r} given but no TOML parser is "
                    "available (python < 3.11 without tomli)")
            # dtpu: ignore[blocking-call-in-async] -- tiny local settings file, read once at process startup (allowed-to-block leaf)
            with open(toml_path, "rb") as fh:
                data: dict[str, Any] = tomllib.load(fh)
            for field in dataclasses.fields(cls):
                if field.name in data:
                    value = data[field.name]
                    if field.name == "overload" and isinstance(value, dict):
                        value = OverloadConfig(**value)
                    if field.name == "slo" and isinstance(value, dict):
                        value = SloConfig(**value)
                    setattr(cfg, field.name, value)
        cfg.coordinator_url = _env("COORDINATOR_URL", cfg.coordinator_url)
        cfg.static_mode = _env_bool("STATIC_MODE", cfg.static_mode)
        cfg.namespace = _env("NAMESPACE", cfg.namespace)
        cfg.lease_ttl_s = _env_float("LEASE_TTL_S", cfg.lease_ttl_s)
        cfg.bind_host = _env("BIND_HOST", cfg.bind_host)
        cfg.advertise_host = _env("ADVERTISE_HOST", cfg.advertise_host)
        cfg.system_enabled = _env_bool("SYSTEM_ENABLED", cfg.system_enabled)
        cfg.system_port = _env_int("SYSTEM_PORT", cfg.system_port)
        cfg.num_worker_threads = _env_int("NUM_WORKER_THREADS", cfg.num_worker_threads)
        cfg.shutdown_timeout_s = _env_float("SHUTDOWN_TIMEOUT_S", cfg.shutdown_timeout_s)
        cfg.retire_drain_s = _env_float("RETIRE_DRAIN_S", cfg.retire_drain_s)
        cfg.stream_idle_timeout_s = _env_float(
            "STREAM_IDLE_TIMEOUT_S", cfg.stream_idle_timeout_s)
        _apply_scalar_env("OVERLOAD", cfg.overload)
        _apply_scalar_env("SLO", cfg.slo)
        return cfg

    @property
    def coordinator_addr(self) -> tuple[str, int]:
        url = self.coordinator_url
        if "://" in url:
            url = url.split("://", 1)[1]
        host, _, port = url.rpartition(":")
        return host or "127.0.0.1", int(port)
