"""Endpoint server: the ingress half of the request/response plane.

Capability parity with reference PushEndpoint/push_handler (lib/runtime/src/
pipeline/network/ingress/push_endpoint.rs:21, push_handler.rs). Differences by
design: the reference receives requests over NATS and streams responses back on
a TCP socket the *caller* registered (egress/addressed_router.rs:69,153); on TPU
pods we run a plain duplex framed-TCP server per endpoint instance — one
connection carries many concurrent request streams, multiplexed by request id —
which removes the NATS hop from the hot path. Control messages Stop/Kill mirror
ControlMessage (network.rs:56-78).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.errors import (AdapterNotFoundError,
                                       InvalidRequestError, OverloadedError,
                                       RateLimitedError, RoleTransitionError)
from dynamo_tpu.runtime.frame import read_frame, write_frame
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import span

log = get_logger("service")


class EndpointServer:
    def __init__(self, runtime, endpoint: Endpoint,
                 handler: Callable[[Any, Context], AsyncIterator[Any]],
                 graceful_shutdown: bool = True,
                 metrics_labels: dict[str, str] | None = None):
        self._runtime = runtime
        self._endpoint = endpoint
        self._handler = handler
        self._graceful = graceful_shutdown
        self._server: asyncio.AbstractServer | None = None
        self._conn_writers: set[asyncio.StreamWriter] = set()
        self._inflight: dict[str, tuple[asyncio.Task, Context]] = {}
        self._stopping = asyncio.Event()
        # Why this server is draining ("role_flip", ...): rides the
        # typed incomplete frames so the client's migration layer can
        # attribute the retry cost (llm/recorder.py migration_reason).
        self._drain_reason: str | None = None
        self.metrics_labels = metrics_labels or {}
        self.instance: Instance | None = None
        comp = endpoint.component
        metrics = (runtime.metrics.namespace(comp.namespace)
                   .component(comp.name).endpoint(endpoint.name))
        # Reference metric names: work-handler request counters/latency
        # (lib/runtime/src/pipeline/network/ingress/push_handler.rs).
        self._m_requests = metrics.counter(
            "requests_total", "Requests received by this endpoint")
        self._m_errors = metrics.counter(
            "request_errors_total", "Requests that ended in error")
        self._m_inflight = metrics.gauge(
            "inflight_requests", "Currently executing requests")
        self._m_duration = metrics.histogram(
            "request_duration_seconds", "Request handling latency")

    async def start(self) -> None:
        cfg = self._runtime.config
        self._server = await asyncio.start_server(
            self._handle_conn, cfg.bind_host, 0)
        port = self._server.sockets[0].getsockname()[1]
        self.instance = Instance(
            namespace=self._endpoint.component.namespace,
            component=self._endpoint.component.name,
            endpoint=self._endpoint.name,
            instance_id=self._runtime.instance_id,
            host=self._runtime.advertise_host,
            port=port,
        )
        if self._runtime.has_discovery:
            # Registration rides the primary lease: process death => lease
            # expiry => delete event => clients drop us (SURVEY.md §5.3).
            # metrics_labels travel with the registration for scrapers/planner.
            try:
                await self._register()
            except BaseException:
                # Registration failed (coordinator down mid-role-flip):
                # release the listening socket so the caller's retry
                # doesn't leak one bound server per attempt.
                self._server.close()
                raise
            self._runtime.coordinator_client.on_lease_recreated(
                self._on_lease_recreated)
        log.info("endpoint %s serving as instance %x on %s:%d",
                 self._endpoint.path, self.instance.instance_id,
                 self.instance.host, port)

    async def _register(self) -> None:
        data = self.instance.to_wire()
        if self.metrics_labels:
            data["labels"] = self.metrics_labels
        await self._runtime.coordinator_client.kv_put(
            self.instance.path, data, use_primary_lease=True)

    async def _on_lease_recreated(self, _new_lease_id: int) -> None:
        """Primary lease was lost and re-granted: re-register so traffic
        doesn't silently drain away."""
        if not self._stopping.is_set():
            await self._register()

    @property
    def port(self) -> int:
        assert self.instance is not None
        return self.instance.port

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        send_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with send_lock:
                await write_frame(writer, obj, chaos_site="service")

        conn_tasks: set[asyncio.Task] = set()
        self._conn_writers.add(writer)
        try:
            while True:
                msg = await read_frame(reader)
                t = msg.get("t")
                if t == "req":
                    rid = msg["rid"]
                    if self._stopping.is_set():
                        # Draining: refuse new work so callers retry elsewhere.
                        await send({"t": "err", "rid": rid,
                                    "e": self._incomplete_wire()})
                        continue
                    ctx = Context.from_wire(msg.get("ctx"))
                    ctx.values["request_id"] = rid
                    task = asyncio.create_task(
                        self._run_request(rid, msg.get("p"), ctx, send))
                    self._inflight[rid] = (task, ctx)
                    conn_tasks.add(task)
                    task.add_done_callback(conn_tasks.discard)
                elif t == "stop":
                    entry = self._inflight.get(msg["rid"])
                    if entry:
                        entry[1].stop_generating()
                elif t == "kill":
                    entry = self._inflight.get(msg["rid"])
                    if entry:
                        entry[1].kill()
                        entry[0].cancel()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            # Caller vanished: kill its in-flight work.
            for task in conn_tasks:
                task.cancel()
            self._conn_writers.discard(writer)
            writer.close()

    def _incomplete_wire(self) -> str:
        """The incomplete-stream wire token, carrying the drain reason
        when one is set ("incomplete:role_flip"). The client splits on
        ':' and surfaces the suffix as StreamIncompleteError.reason."""
        if self._drain_reason:
            return f"incomplete:{self._drain_reason}"
        return "incomplete"

    async def _run_request(self, rid: str, request: Any, ctx: Context,
                           send) -> None:
        self._m_requests.inc()
        self._m_inflight.inc()
        started = time.monotonic()
        # Per-stream sequence numbers: data frames carry "s"=0,1,2,... and
        # the final frame carries the total, so the client can DETECT a
        # lost or duplicated frame (a worker bug, or injected chaos) and
        # fail typed (StreamIncompleteError -> migration) instead of
        # silently delivering a short stream.
        seq = 0
        try:
            # The ctx ids arrived on the wire frame (Context.to_wire
            # carries the traceparent), so this span joins the CALLER's
            # trace: frontend http.request -> this worker.request — and
            # publishes trace_id/span_id to the log formatters for the
            # whole handler task.
            with span("worker.request", ctx=ctx,
                      endpoint=self._endpoint.path):
                async for response in self._handler(request, ctx):
                    if ctx.is_killed:
                        break
                    await send({"t": "data", "rid": rid, "p": response,
                                "s": seq})
                    seq += 1
            if ctx.is_killed:
                # A kill issued by our own drain (shutdown) is an
                # incomplete stream — the caller should migrate it — not
                # a client-initiated kill echo.
                await send({"t": "err", "rid": rid,
                            "e": (self._incomplete_wire()
                                  if self._stopping.is_set() else "killed")})
            else:
                await send({"t": "final", "rid": rid, "s": seq})
        except asyncio.CancelledError:
            if self._stopping.is_set():
                # Drain deadline hit (shutdown cancelled us): send the
                # typed incomplete frame — with the drain reason — so the
                # caller's migration layer re-issues immediately and can
                # attribute the retry, instead of waiting for TCP close.
                self._m_errors.inc()
                try:
                    await send({"t": "err", "rid": rid,
                                "e": self._incomplete_wire()})
                except (ConnectionError, OSError):
                    pass
            raise
        except AdapterNotFoundError as exc:
            # Unknown LoRA adapter name (engine/lora.py): typed so a
            # remote frontend answers 404, not 500. Must precede the
            # generic engine-validation branch — it is an EngineError too.
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{AdapterNotFoundError.WIRE_PREFIX}{exc}"})
            except (ConnectionError, OSError):
                pass
        except (ValueError, InvalidRequestError) as exc:
            # Engine request validation (raised as ValueError by the
            # engine, or already typed by llm-layer code): type it on the
            # wire so the frontend can answer 400, not 500.
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{InvalidRequestError.WIRE_PREFIX}{exc}"})
            except (ConnectionError, OSError):
                pass
        except OverloadedError as exc:
            # SLA admission rejection: type it on the wire so a REMOTE
            # frontend answers 503 (router retries elsewhere), not 500 —
            # in-process deployments already see the class directly.
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{OverloadedError.WIRE_PREFIX}{exc}"})
            except (ConnectionError, OSError):
                pass
        except RateLimitedError as exc:
            # Client-pacing rejection (deadline/priority shed): typed so
            # a remote frontend answers 429, not 500.
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{RateLimitedError.WIRE_PREFIX}{exc}"})
            except (ConnectionError, OSError):
                pass
        except RoleTransitionError as exc:
            # SetRole control-verb rejection (stale epoch, flip already
            # in flight): typed so a remote planner/operator sees the
            # fencing decision, not a generic 500.
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{RoleTransitionError.WIRE_PREFIX}{exc}"})
            except (ConnectionError, OSError):
                pass
        except GeneratorExit:
            # Handler signals an incomplete stream (migration trigger;
            # reference docs/guides/backend.md §Migrate).
            self._m_errors.inc()
            try:
                await send({"t": "err", "rid": rid, "e": "incomplete"})
            except (ConnectionError, OSError):
                pass
        except Exception as exc:  # noqa: BLE001 — ship to caller
            self._m_errors.inc()
            log.warning("handler error for %s: %s", rid, exc, exc_info=True)
            try:
                await send({"t": "err", "rid": rid,
                            "e": f"{type(exc).__name__}: {exc}"})
            except (ConnectionError, OSError):
                pass
        finally:
            self._m_inflight.dec()
            self._m_duration.observe(time.monotonic() - started)
            self._inflight.pop(rid, None)

    async def shutdown(self, drain_s: float | None = None,
                       reason: str | None = None) -> None:
        """Deregister, then drain (graceful) or cancel (fast) in-flight work.
        Reference: serve_endpoint(graceful_shutdown=...) — decode workers exit
        fast so streams migrate (vllm main.py:151-161).

        ``drain_s`` overrides the constructed graceful/fast choice for
        this call: a positive value drains in-flight streams up to that
        deadline even on a fast-shutdown server (role flips reuse the
        retire/migration drain window); streams still running at the
        deadline are killed with a typed incomplete frame. ``reason``
        tags those frames ("incomplete:<reason>") so the caller's
        migration layer can attribute the retry."""
        self._drain_reason = reason or self._drain_reason
        self._stopping.set()
        if self._runtime.has_discovery and self.instance is not None:
            try:
                await self._runtime.coordinator_client.kv_delete(self.instance.path)
            except (ConnectionError, RuntimeError):
                pass
        if drain_s is not None:
            graceful, budget = drain_s > 0, drain_s
        else:
            graceful = self._graceful
            budget = self._runtime.config.shutdown_timeout_s
        if graceful:
            deadline = time.monotonic() + budget
            while self._inflight and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
        victims = list(self._inflight.values())
        for task, ctx in victims:
            ctx.kill()
            task.cancel()
        if victims:
            # Let the killed handlers flush their typed incomplete frames
            # (the migration trigger) before the sockets close under
            # them; bounded so a wedged handler can't stall shutdown.
            try:
                await asyncio.wait_for(
                    asyncio.gather(*(t for t, _ in victims),
                                   return_exceptions=True), 2.0)
            except asyncio.TimeoutError:
                pass
        if self._server:
            self._server.close()
            # Python 3.12 wait_closed() blocks until every connection handler
            # finishes; close peer connections so it can.
            for writer in list(self._conn_writers):
                writer.close()
            await self._server.wait_closed()

    async def wait(self) -> None:
        if self._server:
            await self._server.serve_forever()
