"""Benchmark: steady-state decode throughput of the TPU engine on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Workload: qwen2.5-0.5b-shaped model (random bf16 weights), full 32-sequence
continuous-batching decode with paged attention, ISL 128 / steady decode.
``vs_baseline`` compares per-chip decode token throughput against the
reference's published per-GPU decode example (BASELINE.md: 51.22 tok/s/GPU
per-request ITL at TP4 on an unspecified NVIDIA node — the only absolute
number the reference publishes; config ladder step 1-2 equivalent).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from dynamo_tpu.engine.config import EngineConfig, PRESETS
    from dynamo_tpu.engine.runner import ModelRunner

    spec = PRESETS["qwen2.5-0.5b"]
    batch = 32
    isl = 128
    page = 16
    maxp = 64  # up to 1024 tokens/seq
    config = EngineConfig(
        model=spec, page_size=page, num_pages=batch * maxp + 16,
        max_pages_per_seq=maxp, max_num_seqs=batch,
        prefill_buckets=(128, 256, 512, 1024),
        max_prefill_tokens=1024, attention_backend="auto")
    runner = ModelRunner(config)
    rng = np.random.default_rng(0)

    # Prefill all sequences (measures TTFT path; timed separately).
    pages_per_seq = isl // page
    t0 = time.monotonic()
    for b in range(batch):
        prompt = rng.integers(0, spec.vocab_size, size=isl).astype(np.int32)
        pages = np.arange(1 + b * maxp, 1 + b * maxp + pages_per_seq,
                          dtype=np.int32)
        runner.prefill(prompt, 0, pages, None, (0.0, 0, 1.0))
    prefill_s = time.monotonic() - t0

    # Decode state.
    tokens = rng.integers(0, spec.vocab_size, size=batch).astype(np.int32)
    positions = np.full(batch, isl, np.int32)
    page_table = np.zeros((batch, maxp), np.int32)
    for b in range(batch):
        page_table[b] = np.arange(1 + b * maxp, 1 + (b + 1) * maxp)
    seq_lens = np.full(batch, isl + 1, np.int32)
    temp = np.zeros(batch, np.float32)
    top_k = np.zeros(batch, np.int32)
    top_p = np.ones(batch, np.float32)

    def step():
        nonlocal tokens, positions, seq_lens
        sampled = runner.decode(tokens, positions, page_table, seq_lens,
                                temp, top_k, top_p)
        tokens = sampled
        positions = positions + 1
        seq_lens = seq_lens + 1
        return sampled

    # Warmup (compile) + steady-state measurement.
    for _ in range(3):
        step()
    steps = 64
    t0 = time.monotonic()
    for _ in range(steps):
        step()
    elapsed = time.monotonic() - t0
    tok_s = batch * steps / elapsed
    itl_ms = 1e3 * elapsed / steps
    baseline_decode_tok_s = 51.22  # BASELINE.md profiler example, tok/s/GPU
    print(json.dumps({
        "metric": "decode_tok_s_per_chip_qwen2.5-0.5b_bs32_isl128",
        "value": round(tok_s, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s / baseline_decode_tok_s, 3),
        "detail": {
            "itl_ms_batch": round(itl_ms, 3),
            "prefill_s_total": round(prefill_s, 3),
            "prefill_tok_s": round(batch * isl / prefill_s, 1),
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "attention": config.attention_backend,
        },
    }))


if __name__ == "__main__":
    main()
