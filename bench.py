"""Benchmark: steady-state serving throughput of the TPU engine on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "detail"}.

Workload: qwen2.5-0.5b-shaped model (random bf16 weights) served through the
FULL TPUEngine path — batched prefill, M-step decode windows, continuous
batching — with BENCH_BATCH concurrent requests, ISL 128 / OSL 128
(BENCH_BATCH / BENCH_ISL / BENCH_OSL / BENCH_MODEL / BENCH_WINDOW /
BENCH_DEPTH env vars override; docs/PERF_NOTES.md records the sweep behind
the defaults). A full-shape warmup round compiles every bucket first; then
BENCH_ROUNDS (default 3) measured rounds run and the MEDIAN round (by
decode tok/s) is reported with min/max spread — a single round through the
tunneled chip occasionally throws a wild outlier (round-3 VERDICT weak #1),
and the SLA claim must hold across repeats, not once.

Defaults: bs40/M=32/D=4 — one notch below the bs48 throughput optimum,
chosen so p99 TTFT holds the 500 ms north-star SLO with ~100 ms headroom
under environment variance (the driver's round-3 capture measured 651 ms
at the zero-headroom bs48 default; PERF_NOTES "SLA headroom" section).

``vs_baseline`` is the fraction of the chip's own bf16 weight-read
roofline that the measured decode throughput achieves (hardware-anchored,
same-workload). The reference publishes NO comparable absolute number
(BASELINE.md: its only in-repo figures are a 70B-class TP4 profiler
example), so a cross-hardware ratio against its 51.22 tok/s/GPU decode
ITL example — headlined in earlier rounds — was apples-to-oranges and is
now in ``detail.ref_example_ratio`` with that caveat attached.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import numpy as np

def perf_snapshot(engine) -> dict:
    """The perf-plane section every bench JSON embeds (scripts/
    perf_gate.py diffs it against a committed baseline): per-program
    compile counts/seconds, the unexpected-recompile total (MUST be 0
    in steady state), and the roofline-attributed window series."""
    from dynamo_tpu.engine import perf
    reg = perf.get_registry()
    return {"compiles": reg.snapshot(), "window": reg.window_snapshot(),
            "hbm": engine.runner.hbm_stats(),
            "memory": engine.runner.memory_breakdown()}


ISL = int(os.environ.get("BENCH_ISL", "128"))
OSL = int(os.environ.get("BENCH_OSL", "128"))
BATCH = int(os.environ.get("BENCH_BATCH", "40"))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "3"))
# Mixed-workload mode (BENCH_MIXED=1 or --mode mixed): long prompts
# arriving mid-steady-decode; the headline is the steady decoders'
# itl_gap_p99 DURING prefill interference (stall-free chunked prefill,
# docs/PERF_NOTES.md "Stall-free prefill").
LONG_ISL = int(os.environ.get("BENCH_LONG_ISL", "4096"))
LONG_N = int(os.environ.get("BENCH_LONG_N", "4"))
# HBM bandwidth lives in ModelSpec.weight_read_step_ms (env DTPU_HBM_GBPS,
# default v5e 819 GB/s) so bench, auto-window sizing, and profiling agree.


async def run_round(engine, spec, rng, tag, batch=BATCH, osl=OSL):
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    async def one(i):
        prompt = rng.integers(0, spec.vocab_size, size=ISL).tolist()
        req = PreprocessedRequest(model="bench", token_ids=prompt)
        req.stop_conditions.max_tokens = osl
        req.stop_conditions.ignore_eos = True
        t_submit = time.monotonic()
        t_first = None
        arrivals = []  # (t, n_tokens)
        async for out in engine.generate(req, Context()):
            n = len(out.get("token_ids", []))
            now = time.monotonic()
            if n and t_first is None:
                t_first = now
            if n:
                arrivals.append((now, n))
            if out.get("finish_reason"):
                break
        return t_submit, t_first, arrivals

    t0 = time.monotonic()
    results = await asyncio.gather(*[one(i) for i in range(batch)])
    elapsed = time.monotonic() - t0
    ttfts = [t_first - t_submit for t_submit, t_first, _ in results]
    total_tokens = sum(sum(n for _, n in arr) for _, _, arr in results)
    itl_means = []
    gaps = []  # true per-token inter-arrival gaps (tokens arrive in
    # window-sized bursts: in-burst gaps are ~0, burst gaps ~window time)
    decode_tokens = 0
    decode_span = 0.0
    for _, t_first, arr in results:
        n_after_first = sum(n for _, n in arr) - arr[0][1]
        span = arr[-1][0] - t_first
        if n_after_first > 0 and span > 0:
            itl_means.append(span / n_after_first)
            decode_tokens += n_after_first
            decode_span = max(decode_span, span)
        for (t_prev, _), (t_cur, n_cur) in zip(arr, arr[1:]):
            gaps.append(t_cur - t_prev)       # first token of the burst
            gaps.extend([0.0] * (n_cur - 1))  # rest arrive together
    return {
        "elapsed_s": elapsed,
        "total_tokens": total_tokens,
        "decode_tok_s": decode_tokens / decode_span if decode_span else 0.0,
        "ttft_p50_ms": 1e3 * float(np.percentile(ttfts, 50)),
        "ttft_p99_ms": 1e3 * float(np.percentile(ttfts, 99)),
        "itl_mean_ms": 1e3 * float(np.mean(itl_means)) if itl_means else 0.0,
        "itl_gap_p99_ms": 1e3 * float(np.percentile(gaps, 99)) if gaps
        else 0.0,
    }


async def run_mixed(engine, spec, rng):
    """Steady decoders + LONG_N long prompts injected mid-decode.

    Returns the steady decoders' inter-burst gap p99 split into the
    interference window (first long submitted -> last long's first
    token) vs outside it, plus the longs' TTFTs. With stall-free
    chunked prefill the two p99s should be within ~one chunk's compute;
    the pre-rework engine stalled every decoder for the WHOLE long
    prompt (one gap >= full prefill per long)."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context

    window = {"t0": None, "t1": None}
    first_tokens = asyncio.Event()
    started = 0

    async def steady(i):
        nonlocal started
        prompt = rng.integers(0, spec.vocab_size, size=ISL).tolist()
        req = PreprocessedRequest(model="bench", token_ids=prompt)
        req.stop_conditions.max_tokens = OSL
        req.stop_conditions.ignore_eos = True
        arrivals = []
        async for out in engine.generate(req, Context()):
            n = len(out.get("token_ids", []))
            if n:
                arrivals.append((time.monotonic(), n))
                if len(arrivals) == 1:
                    started += 1
                    if started >= BATCH:
                        first_tokens.set()
            if out.get("finish_reason"):
                break
        return arrivals

    async def long_one(i):
        prompt = rng.integers(0, spec.vocab_size, size=LONG_ISL).tolist()
        req = PreprocessedRequest(model="bench", token_ids=prompt)
        req.stop_conditions.max_tokens = 8
        req.stop_conditions.ignore_eos = True
        t_submit = time.monotonic()
        t_first = None
        async for out in engine.generate(req, Context()):
            if out.get("token_ids") and t_first is None:
                t_first = time.monotonic()
            if out.get("finish_reason"):
                break
        return t_submit, t_first

    steady_tasks = [asyncio.ensure_future(steady(i)) for i in range(BATCH)]
    await first_tokens.wait()
    window["t0"] = time.monotonic()
    long_results = await asyncio.gather(
        *[long_one(i) for i in range(LONG_N)])
    window["t1"] = max(t for _, t in long_results)
    steady_results = await asyncio.gather(*steady_tasks)
    gaps_in, gaps_out = [], []
    for arrivals in steady_results:
        for (t_prev, _), (t_cur, n_cur) in zip(arrivals, arrivals[1:]):
            gap = t_cur - t_prev
            bucket = (gaps_in if window["t0"] <= t_cur <= window["t1"]
                      else gaps_out)
            bucket.append(gap)
            bucket.extend([0.0] * (n_cur - 1))
    ttfts = [t1 - t0 for t0, t1 in long_results]
    p99 = lambda xs: 1e3 * float(np.percentile(xs, 99)) if xs else 0.0
    return {
        "itl_gap_p99_ms_during_prefill": p99(gaps_in),
        "itl_gap_p99_ms_steady": p99(gaps_out),
        "itl_gap_max_ms_during_prefill":
            1e3 * max(gaps_in) if gaps_in else 0.0,
        "long_ttft_p50_ms": 1e3 * float(np.percentile(ttfts, 50)),
        "long_ttft_max_ms": 1e3 * max(ttfts),
        "interference_window_s": window["t1"] - window["t0"],
    }


async def main_async(mode: str = "serve"):
    import jax

    from dynamo_tpu.engine.config import EngineConfig, PRESETS
    from dynamo_tpu.engine.engine import TPUEngine

    spec = PRESETS[os.environ.get("BENCH_MODEL", "qwen2.5-0.5b")]
    # int8 weights by default: measured faster AND more SLO headroom than
    # bf16 at the default config (21.9K vs 18.0K tok/s, TTFT p99 343 vs
    # 428 ms), with quality CI-gated (tests/test_quant.py). BENCH_QUANT
    # overrides; "none" selects bf16.
    quant = os.environ.get("BENCH_QUANT", "int8")
    if quant and quant != "none":
        import dataclasses
        spec = dataclasses.replace(spec, quant=quant)
    # KV-cache quantization (engine/kv_quant.py): BENCH_QUANT_KV=int8
    # opts in; "none"/unset keeps bf16 KV so committed baselines stay
    # like-for-like. The kv-quant config is embedded in detail either
    # way so scripts/perf_gate.py can tell the configurations apart.
    quant_kv = os.environ.get("BENCH_QUANT_KV", "none")
    quant_kv = None if quant_kv in ("", "none") else quant_kv
    page = 16
    maxp = 64  # up to 1024 tokens/seq
    seqs = BATCH
    if mode == "mixed":
        # Long prompts need room (LONG_ISL + outputs), and the longs ride
        # ALONGSIDE the steady batch. Page budget: steady seqs at their
        # full length + the longs at theirs.
        maxp = max(maxp, -(-(LONG_ISL + 64) // page))
        seqs = BATCH + LONG_N
    steady_pages = BATCH * (-(-(ISL + OSL) // page))
    config = EngineConfig(
        model=spec, page_size=page,
        num_pages=(steady_pages + LONG_N * maxp + 16 if mode == "mixed"
                   else BATCH * 64 + 16),
        max_pages_per_seq=maxp, max_num_seqs=seqs,
        prefill_buckets=(128, 256, 512, 1024),
        max_prefill_tokens=int(os.environ.get("BENCH_MAX_PREFILL", "1024")),
        attention_backend=os.environ.get("BENCH_ATTN", "auto"),
        decode_window=int(os.environ.get("BENCH_WINDOW", "32")),
        pipeline_depth=int(os.environ.get("BENCH_DEPTH", "4")),
        prefill_chunk_tokens=os.environ.get("BENCH_CHUNK_TOKENS", "auto")
        if not os.environ.get("BENCH_CHUNK_TOKENS", "auto").isdigit()
        else int(os.environ["BENCH_CHUNK_TOKENS"]),
        quant_kv=quant_kv)
    engine = TPUEngine(config)
    engine.start()
    rng = np.random.default_rng(0)

    if mode == "prefill":
        # Worker-level prefill bench: the disaggregated prefill worker's
        # serving pattern (every request is prompt -> first token). The
        # engine dispatches NO decode windows for these slots.
        await run_round(engine, spec, rng, "warmup", osl=1)
        pres = [await run_round(engine, spec, rng, f"prefill{i}", osl=1)
                for i in range(max(3, ROUNDS))]
        by_el = sorted(r["elapsed_s"] for r in pres)
        med_round = sorted(pres, key=lambda r: r["elapsed_s"])[len(pres) // 2]
        med = BATCH * ISL / by_el[len(by_el) // 2]
        perf = perf_snapshot(engine)
        engine.stop()
        print(json.dumps({
            "metric": f"prefill_tok_s_per_chip_{spec.name}_bs{BATCH}"
                      f"_isl{ISL}",
            "value": round(med, 1),
            "unit": "tok/s/chip",
            "vs_baseline": round(
                med / (BATCH * ISL / by_el[0]), 3) if by_el[0] else 0.0,
            "detail": {
                "vs_baseline_semantics": "median/best across rounds "
                                         "(stability; 1.0 = no outliers)",
                "rounds": [round(BATCH * ISL / e, 1) for e in by_el],
                "ttft_p99_ms": round(med_round["ttft_p99_ms"], 1),
                "quant": spec.quant,
                "quant_kv": config.quant_kv,
                "quant": spec.quant,
            "quant_kv": config.quant_kv,
            "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
                "perf": perf,
            },
        }))
        return

    if mode == "mixed":
        # Warm every bucket incl. the chunk/history variants, then run
        # the interference rounds; the headline is the steady decoders'
        # gap p99 DURING long-prompt prefill.
        await run_round(engine, spec, rng, "warmup", batch=4, osl=8)
        warm = await run_mixed(engine, spec, rng)  # compiles long path
        rounds_m = [await run_mixed(engine, spec, rng)
                    for _ in range(max(1, ROUNDS))]
        med = sorted(rounds_m,
                     key=lambda r: r["itl_gap_p99_ms_during_prefill"])[
                         len(rounds_m) // 2]
        perf = perf_snapshot(engine)
        engine.stop()
        steady_p99 = med["itl_gap_p99_ms_steady"]
        during_p99 = med["itl_gap_p99_ms_during_prefill"]
        print(json.dumps({
            "metric": f"mixed_itl_gap_p99_ms_during_prefill_{spec.name}"
                      f"_bs{BATCH}_long{LONG_ISL}x{LONG_N}",
            "value": round(during_p99, 3),
            "unit": "ms",
            # 1.0 = stall-free ideal (interference-window gap p99 equals
            # the steady-state gap p99); the pre-rework engine stalled
            # decoders for the whole long prefill.
            "vs_baseline": round(steady_p99 / during_p99, 3)
            if during_p99 else 0.0,
            "detail": {
                "vs_baseline_semantics": "steady gap p99 / during-prefill "
                                         "gap p99 (1.0 = no decode stall "
                                         "from long-prompt prefill)",
                "rounds": [
                    {k: round(v, 3) for k, v in r.items()}
                    for r in rounds_m],
                "warmup_round": {k: round(v, 3) for k, v in warm.items()},
                "prefill_chunk_tokens": engine.prefill_chunk_tokens,
                "decode_window": config.decode_window,
                "quant": spec.quant,
                "quant_kv": config.quant_kv,
                "quant": spec.quant,
            "quant_kv": config.quant_kv,
            "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
                "perf": perf,
            },
        }))
        return

    t0 = time.monotonic()
    await run_round(engine, spec, rng, "warmup")  # compiles all buckets
    warm_s = time.monotonic() - t0
    rounds = [await run_round(engine, spec, rng, f"steady{i}")
              for i in range(max(1, ROUNDS))]
    # Median round by decode throughput; spread shows run-to-run variance
    # (tunnel outliers, host contention) so one lucky/unlucky round can't
    # carry the claim.
    by_tok_s = sorted(rounds, key=lambda r: r["decode_tok_s"])
    steady = by_tok_s[len(by_tok_s) // 2]
    spread = {
        "rounds": len(rounds),
        "decode_tok_s": [round(r["decode_tok_s"], 1) for r in rounds],
        "ttft_p99_ms": [round(r["ttft_p99_ms"], 1) for r in rounds],
        "ttft_p99_ms_worst": round(max(r["ttft_p99_ms"] for r in rounds), 1),
        "decode_tok_s_min": round(by_tok_s[0]["decode_tok_s"], 1),
        "decode_tok_s_max": round(by_tok_s[-1]["decode_tok_s"], 1),
    }
    # Concurrency sweep (VERDICT r2 weak #8: one ISL/OSL/bs point isn't
    # steady-state evidence): same engine, lower concurrency.
    sweep = {}
    for bs in (8, 16):
        r = await run_round(engine, spec, rng, f"bs{bs}", batch=bs)
        sweep[f"bs{bs}_decode_tok_s"] = round(r["decode_tok_s"], 1)
    # MEASURED prefill throughput: max_tokens=1 rounds — the clock stops
    # when every first token has arrived (not the TTFT-derived proxy).
    # Median of 3: a single tunnel stall once reported 240 tok/s for a
    # round whose own TTFT implied ~15K (round-4 capture); one outlier
    # round must not carry (or sink) the claim.
    pres = [await run_round(engine, spec, rng, f"prefill{i}", osl=1)
            for i in range(3)]
    pre_elapsed = sorted(r["elapsed_s"] for r in pres)
    prefill_tok_s_measured = BATCH * ISL / pre_elapsed[1]
    prefill_spread = [round(BATCH * ISL / e, 1) for e in pre_elapsed]
    perf = perf_snapshot(engine)
    engine.stop()

    # Roofline context: one decode step must read all weights once.
    step_floor_ms = spec.weight_read_step_ms()
    roofline_tok_s = BATCH / (step_floor_ms / 1e3)
    tok_s = steady["decode_tok_s"]
    print(json.dumps({
        "metric": f"decode_tok_s_per_chip_{spec.name}_bs{BATCH}_isl{ISL}",
        "value": round(tok_s, 2),
        "unit": "tok/s/chip",
        # Fraction of this chip's bf16 weight-read roofline for this
        # batch — the honest same-hardware baseline (see module docstring).
        "vs_baseline": round(tok_s / roofline_tok_s, 3),
        "detail": {
            "vs_baseline_semantics": "fraction of bf16 weight-read "
                                     "roofline on this chip (the "
                                     "reference publishes no comparable "
                                     "absolute number; BASELINE.md)",
            "ttft_p50_ms": round(steady["ttft_p50_ms"], 1),
            "ttft_p99_ms": round(steady["ttft_p99_ms"], 1),
            "itl_mean_ms": round(steady["itl_mean_ms"], 3),
            "itl_gap_p99_ms": round(steady["itl_gap_p99_ms"], 3),
            "spread": spread,
            "osl": OSL,
            "round_s": round(steady["elapsed_s"], 2),
            "prefill_tok_s": round(prefill_tok_s_measured, 1),
            "prefill_tok_s_rounds": prefill_spread,
            "sweep": sweep,
            "warmup_s": round(warm_s, 1),
            "roofline_tok_s_weight_read": round(roofline_tok_s, 0),
            # Cross-hardware, cross-model ratio vs the reference's only
            # absolute figure (51.22 tok/s/GPU decode ITL example on a
            # 70B-class TP4 config) — apples-to-oranges, context only.
            "ref_example_ratio": round(tok_s / 51.22, 1),
            "decode_window": config.decode_window,
            "pipeline_depth": config.pipeline_depth,
            "quant": spec.quant,
            "quant_kv": config.quant_kv,
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "perf": perf,
        },
    }))


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=("serve", "prefill", "mixed"),
                    default=os.environ.get("BENCH_MODE", "serve"),
                    help="serve: full continuous-batching bench (default); "
                         "prefill: disagg prefill-worker pattern "
                         "(max_tokens=1 bursts, headline = prefill tok/s); "
                         "mixed: long prompts injected mid-steady-decode "
                         "(headline = decode itl_gap_p99 during prefill "
                         "interference; also BENCH_MIXED=1)")
    args = ap.parse_args()
    if os.environ.get("BENCH_MIXED") == "1":
        args.mode = "mixed"
    asyncio.run(main_async(args.mode))
    # Hard-exit after the JSON line: interpreter teardown races the
    # tunnel client's destructor and prints a harmless-but-ugly Rust
    # panic ("AxonClient not initialized") into every driver capture.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
